"""graftcost CLI: train the program-cost model and report its accuracy.

    python tools/graftcost.py --report              # persisted + live labels
    python tools/graftcost.py --report --selftest   # mint labels first

``--report`` loads the persisted compile/run-ms label history (the
``labels`` satellite of the shape-hint file, KMAMIZ_SHAPE_HINTS), merges
the live registry's labels, fits the ridge head, and prints one JSON
document with the fit report plus a per-row predicted-vs-actual
compile-ms table — the "is the model earning its keep" surface the docs
quote. ``--selftest`` first exercises a small EndpointGraph ramp so the
report works in a fresh checkout with no hint file: the minted labels
are real measured compiles, not fixtures.

Exit code: 0 when a fit happened, 2 when there were no labelled rows
(nothing persisted, nothing live — run with --selftest).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _selftest_labels() -> None:
    """Mint live compile/run labels: a small segment-store ramp through
    one consolidation (every program the predictive-prewarm path cares
    about compiles at least once, with measured walls)."""
    import numpy as np

    from kmamiz_tpu.graph.store import EndpointGraph

    gg = EndpointGraph(capacity=256, tenant="graftcost-selftest")
    rows = 200
    for i in range(4):
        k = np.arange(i * rows, (i + 1) * rows)
        gg.merge_edges(
            (k % 97).astype(np.int32),
            (k // 97).astype(np.int32),
            np.full(rows, 1 + i % 5, dtype=np.int32),
        )
        gg.n_edges  # finalize: compile labels land in the registry


def build_report(selftest: bool = False) -> dict:
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.cost.model import CostModel, training_rows

    if selftest:
        _selftest_labels()
    persisted = programs.load_labels()
    rows = training_rows(persisted)
    report = {
        "hintsPath": programs.hints_path(),
        "persistedPrograms": len(persisted),
        "rows": len(rows),
        "fit": None,
        "table": [],
    }
    if not rows:
        return report
    model = CostModel()
    report["fit"] = model.fit(rows)
    preds = model.predict_many([(name, spec) for name, spec, _c, _r in rows])
    table = []
    for (name, spec, compile_ms, run_ms), pred in zip(rows, preds):
        table.append(
            {
                "program": name,
                "actualCompileMs": round(float(compile_ms), 2),
                "predictedCompileMs": round(float(pred[0]), 2),
                "errorCompileMs": round(float(pred[0]) - float(compile_ms), 2),
                "actualRunMs": round(float(run_ms), 3),
                "predictedRunMs": round(float(pred[1]), 3),
            }
        )
    # biggest programs first — the ones boot ranking reorders around
    table.sort(key=lambda r: -r["actualCompileMs"])
    report["table"] = table
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--report", action="store_true", help="fit and print the accuracy report"
    )
    ap.add_argument(
        "--selftest",
        action="store_true",
        help="exercise a small graph ramp first so live labels exist",
    )
    ap.add_argument(
        "--top", type=int, default=20, help="table rows to print (0 = all)"
    )
    args = ap.parse_args(argv)
    if not args.report:
        ap.error("nothing to do: pass --report")
    report = build_report(selftest=args.selftest)
    if args.top and len(report["table"]) > args.top:
        report["tableTruncated"] = len(report["table"]) - args.top
        report["table"] = report["table"][: args.top]
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["fit"] else 2


if __name__ == "__main__":
    sys.exit(main())
