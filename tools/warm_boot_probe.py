"""Restart-warmth probe: boot a fresh process, pre-warm, time the first tick.

Run twice against the same KMAMIZ_COMPILE_CACHE_DIR to measure the
production restart story (VERDICT r4 #5b):

  run 1 (cold cache): the pre-warm pays the real compile walls, once;
  run 2 (warm cache): the pre-warm reloads programs from disk and the
  first tick runs with zero compile exposure.

Prints ONE JSON line: {"prewarm_s": ..., "first_tick_ms": ...,
"second_tick_ms": ...}. bench.py invokes this as a subprocess for the
warm_first_tick_ms extra; it is also a deployable smoke check
(KMAMIZ_COMPILE_CACHE_DIR=/var/cache/kmamiz python tools/warm_boot_probe.py).
"""
from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def main() -> None:
    from kmamiz_tpu.core import compile_cache

    compile_cache.enable_from_env()

    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.synth import make_raw_window

    # the reference-cadence tick: 2,500 traces x 7 spans
    window = json.loads(make_raw_window(2_500, 7))
    dp = DataProcessor(trace_source=lambda lb, t, lim: window)

    t0 = time.perf_counter()
    n_programs = dp.graph.prewarm_compile(hints=((512, 8),))
    prewarm_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    dp.collect({"uniqueId": "warm-1", "lookBack": 30_000, "time": 1_000_000})
    # drain the deferred merge INSIDE the timer: the staged union is the
    # device work the pre-warm exists to keep compile-free, and the
    # second tick below charges it identically (comparable numbers)
    dp.graph.n_edges
    first_tick_ms = (time.perf_counter() - t0) * 1000

    window2 = json.loads(make_raw_window(2_500, 7, t_start=10_000))
    dp2 = DataProcessor(trace_source=lambda lb, t, lim: window2)
    t0 = time.perf_counter()
    dp2.collect({"uniqueId": "warm-2", "lookBack": 30_000, "time": 2_000_000})
    dp2.graph.n_edges
    second_tick_ms = (time.perf_counter() - t0) * 1000

    print(
        json.dumps(
            {
                "prewarm_s": round(prewarm_s, 1),
                "prewarm_programs": n_programs,
                "first_tick_ms": round(first_tick_ms, 1),
                "second_tick_ms": round(second_tick_ms, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
