"""Restart-warmth probe: boot a fresh process, pre-warm, time the first tick.

Run twice against the same KMAMIZ_COMPILE_CACHE_DIR to measure the
production restart story (VERDICT r4 #5b):

  run 1 (cold cache): the boot prewarm plan pays the real compile walls,
  once, and autosaves the exercised bucket shapes into the shape-hint
  file next to the cache dir (core/programs.py);
  run 2 (warm cache): the plan replays exactly those hints — populating
  the jit dispatch caches from the persistent XLA cache — and the first
  tick runs with zero compile exposure.

stdout carries ONE JSON line: {"prewarm_s": ..., "first_tick_ms": ...,
"second_tick_ms": ..., "first_tick_new_compiles": ...,
"second_tick_new_compiles": ..., "programs": {...}}. The per-program
compile-count / compile-ms table goes to stderr. bench.py invokes this as
a subprocess for the warm-boot extras; it is also a deployable smoke
check (KMAMIZ_COMPILE_CACHE_DIR=/var/cache/kmamiz python
tools/warm_boot_probe.py).
"""
from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def _print_program_table(summary: dict) -> None:
    """Per-program compile telemetry, aligned, on stderr (stdout is the
    one-JSON-line machine contract)."""
    rows = [
        (name, st)
        for name, st in sorted(summary["programs"].items())
        if st["calls"] or st["prewarmed"]
    ]
    if not rows:
        return
    width = max(len(name) for name, _ in rows)
    print(
        f"{'program':<{width}}  calls  compiles  compile_ms  "
        "prewarmed  prewarm_ms  buckets",
        file=sys.stderr,
    )
    for name, st in rows:
        print(
            f"{name:<{width}}  {st['calls']:>5}  {st['compiles']:>8}  "
            f"{st['compileMs']:>10.1f}  {st['prewarmed']:>9}  "
            f"{st['prewarmMs']:>10.1f}  {len(st.get('buckets', [])):>7}",
            file=sys.stderr,
        )
    print(
        f"total: {summary['totalCompiles']} compiles, "
        f"{summary['totalCompileMs']:.1f} ms",
        file=sys.stderr,
    )


def main() -> None:
    from kmamiz_tpu.core import compile_cache, programs

    compile_cache.enable_from_env()

    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.synth import make_raw_window

    # the reference-cadence tick: 2,500 traces x 7 spans
    window = json.loads(make_raw_window(2_500, 7))
    dp = DataProcessor(trace_source=lambda lb, t, lim: window)

    # boot prewarm plan: replay persisted shape hints when the previous
    # run recorded them, else the graph-store default buckets — the same
    # plan the server mains dispatch through boot_prewarm_from_env
    t0 = time.perf_counter()
    report = programs.run_prewarm(graph=dp.graph)
    prewarm_s = time.perf_counter() - t0

    snap = programs.snapshot()
    t0 = time.perf_counter()
    dp.collect({"uniqueId": "warm-1", "lookBack": 30_000, "time": 1_000_000})
    # drain the deferred merge INSIDE the timer: the staged union is the
    # device work the pre-warm exists to keep compile-free, and the
    # second tick below charges it identically (comparable numbers)
    dp.graph.n_edges
    first_tick_ms = (time.perf_counter() - t0) * 1000
    first_tick_new = programs.new_compiles_since(snap)

    window2 = json.loads(make_raw_window(2_500, 7, t_start=10_000))
    dp2 = DataProcessor(trace_source=lambda lb, t, lim: window2)
    snap = programs.snapshot()
    t0 = time.perf_counter()
    dp2.collect({"uniqueId": "warm-2", "lookBack": 30_000, "time": 2_000_000})
    dp2.graph.n_edges
    second_tick_ms = (time.perf_counter() - t0) * 1000
    second_tick_new = programs.new_compiles_since(snap)

    summary = programs.summary()
    _print_program_table(summary)
    print(
        json.dumps(
            {
                "prewarm_s": round(prewarm_s, 1),
                "prewarm_programs": report["warmed"]
                + report["defaultGraphPrograms"],
                "prewarm_report": report,
                "first_tick_ms": round(first_tick_ms, 1),
                "second_tick_ms": round(second_tick_ms, 1),
                # steady-state contract: compiles a warm process still
                # paid INSIDE the timed ticks (0 when hints covered all)
                "first_tick_new_compiles": sum(first_tick_new.values()),
                "second_tick_new_compiles": sum(second_tick_new.values()),
                "programs": {
                    name: {
                        "compiles": st["compiles"],
                        "compileMs": round(st["compileMs"], 1),
                        "prewarmed": st["prewarmed"],
                    }
                    for name, st in sorted(summary["programs"].items())
                    if st["calls"] or st["prewarmed"]
                },
            }
        )
    )


if __name__ == "__main__":
    main()
