#!/usr/bin/env python
"""graftlint CLI: lint the kmamiz_tpu package for hot-path invariant drift.

    python tools/graftlint.py                 # report, exit 0
    python tools/graftlint.py --strict        # exit 1 on any unsuppressed
                                              # finding or reason-less
                                              # suppression (what CI runs)
    python tools/graftlint.py --json          # machine-readable output
    python tools/graftlint.py kmamiz_tpu/ops  # lint a subtree
    python tools/graftlint.py --list-rules

KMAMIZ_LINT_STRICT=1 makes --strict the default (used by the tier-1
test and pre-merge hooks). Suppress a finding in source with
`# graftlint: disable=<rule> -- <reason>` on (or directly above) the
flagged line; docs/STATIC_ANALYSIS.md has the full rule catalogue.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kmamiz_tpu.analysis import framework  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: kmamiz_tpu/)")
    ap.add_argument(
        "--strict",
        action="store_true",
        default=os.environ.get("KMAMIZ_LINT_STRICT", "") not in ("", "0"),
        help="exit 1 on unsuppressed findings or reason-less suppressions",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--rules", help="comma-separated rule subset (default: all)"
    )
    ap.add_argument(
        "--verbose", action="store_true", help="also list suppressed findings"
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, r in sorted(framework.all_rules().items()):
            print(f"{name}: {r.doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        result = framework.lint_paths(
            framework.repo_root(), args.paths or None, rules
        )
    except ValueError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(framework.render_json(result))
    else:
        print(framework.render_text(result, verbose=args.verbose))

    if not args.strict:
        return 0
    bad = len(result.findings)
    missing = result.missing_reasons()
    if missing:
        for path, sup in missing:
            print(
                f"graftlint: strict: {path}:{sup.line}: suppression "
                "without a reason (add `-- <why>`)",
                file=sys.stderr,
            )
    return 1 if (bad or missing) else 0


if __name__ == "__main__":
    sys.exit(main())
