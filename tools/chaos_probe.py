"""Chaos probe: prove the four resilience-pillar invariants under a seed.

Everything the fault layer promises (kmamiz_tpu/resilience/,
docs/RESILIENCE.md) is asserted here against the REAL pipeline — native
parse, device graph merge, the DP HTTP server — with faults drawn from a
seeded FaultPlan so a failure reproduces exactly:

  1. quarantine bit-exactness — a chunk stream poisoned per the plan
     (truncated JSON, invalid UTF-8, schema drift, trace bombs, drops)
     ingests to a graph bit-identical (graph_signature) to ingesting
     only the untouched chunks; every poisoned delivery lands in the
     quarantine with a reason code;
  2. breaker state machine — `threshold` consecutive failures OPEN the
     breaker (short-circuits without touching the upstream), cooldown
     admits a HALF-OPEN probe, a failed probe re-opens, a good one
     closes;
  3. degraded serve — with KMAMIZ_TICK_DEADLINE_MS set and the trace
     source hung, POST / on the DP server answers 200 with the
     last-good graph, `stale: true`, the X-KMamiz-Stale-Age-Ms header,
     ZERO new compiles (program-registry snapshot diff), and no 5xx;
  4. crash-safe recovery — a child process ingests with KMAMIZ_WAL=1
     and SIGKILLs itself between the WAL append and the graph merge of
     its final window; a fresh processor's replay_wal() restores a
     graph bit-identical to ingesting every window.

stdout carries ONE JSON line: {"seed": ..., "ok": ..., per-pillar
results, "chaos_recovery_ms": ..., "degraded_serve_ms": ...}. The
human-readable pillar table goes to stderr. Exit 0 iff every pillar
holds. bench.py invokes this as a subprocess for the chaos extras;
`--child-kill` is the internal crash-child mode (never returns).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, "/root/repo")

# clean chunks must fit under this while the plan's "bomb" payloads
# (~4.1 KB, chaos.mutate_payload) overflow it
SIZE_CAP_BYTES = 4000


def _mk_span(tid: str, sid: str, parent=None, svc="svc", url=None) -> dict:
    return {
        "traceId": tid,
        "id": sid,
        "parentId": parent,
        "kind": "SERVER",
        "name": f"{svc}.ns.svc.cluster.local:80/*",
        "timestamp": 1_700_000_000_000_000,
        "duration": 1000,
        "tags": {
            "http.method": "GET",
            "http.status_code": "200",
            "http.url": url or f"http://{svc}.ns/api",
            "istio.canonical_revision": "v1",
            "istio.canonical_service": svc,
            "istio.mesh_id": "cluster.local",
            "istio.namespace": "ns",
        },
    }


def _clean_groups(n_traces: int, prefix: str):
    """n_traces two-span traces fanning out to 5 downstream services —
    enough edge diversity that a silently lost or duplicated trace
    moves the graph signature."""
    groups = []
    for t in range(n_traces):
        tid = f"{prefix}{t}"
        parent = _mk_span(tid, f"{tid}p")
        child = _mk_span(
            tid,
            f"{tid}c",
            parent=f"{tid}p",
            svc=f"down{t % 5}",
            url=f"http://down{t % 5}.ns/api/{t % 3}",
        )
        groups.append([parent, child])
    return groups


def _clean_chunks(n_traces=40, per_chunk=2, prefix="t"):
    groups = _clean_groups(n_traces, prefix)
    chunks = [
        json.dumps(groups[i : i + per_chunk]).encode()
        for i in range(0, len(groups), per_chunk)
    ]
    oversized = [len(c) for c in chunks if len(c) >= SIZE_CAP_BYTES]
    if oversized:
        raise RuntimeError(
            f"clean chunks must stay under the probe size cap: {oversized}"
        )
    return chunks


def _fresh_processor():
    from kmamiz_tpu.server.processor import DataProcessor

    return DataProcessor(trace_source=lambda *a: [], use_device_stats=False)


# -- pillar 1: poison-input quarantine ---------------------------------------


def pillar_quarantine(seed: int, tmpdir: str) -> dict:
    os.environ["KMAMIZ_QUARANTINE_DIR"] = os.path.join(tmpdir, "quarantine")
    os.environ["KMAMIZ_INGEST_MAX_BYTES"] = str(SIZE_CAP_BYTES)
    from kmamiz_tpu.resilience import quarantine as res_quarantine
    from kmamiz_tpu.resilience.chaos import (
        FaultPlan,
        chaos_chunks,
        graph_signature,
    )

    chunks = _clean_chunks()
    delivered, clean_indices = chaos_chunks(chunks, FaultPlan(seed))

    chaos_dp = _fresh_processor()
    quarantined = 0
    for raw in delivered:
        quarantined += chaos_dp.ingest_raw_window(raw).get("quarantined", 0)
    chaos_sig = graph_signature(chaos_dp.graph)

    clean_dp = _fresh_processor()
    for i in clean_indices:
        clean_dp.ingest_raw_window(chunks[i])
    clean_sig = graph_signature(clean_dp.graph)

    stats = res_quarantine.quarantine_stats()
    poisoned = len(delivered) - len(clean_indices)
    return {
        "ok": (
            chaos_sig == clean_sig
            and poisoned > 0
            and quarantined == poisoned
            and stats["count"] == poisoned
        ),
        "chunks": len(chunks),
        "delivered": len(delivered),
        "clean": len(clean_indices),
        "quarantined": quarantined,
        "byReason": stats["byReason"],
        "signature": chaos_sig,
        "bitExact": chaos_sig == clean_sig,
    }


# -- pillar 2: circuit breaker state machine ---------------------------------


def pillar_breaker() -> dict:
    from kmamiz_tpu.resilience.breaker import (
        HALF_OPEN,
        OPEN,
        BreakerOpenError,
        CircuitBreaker,
    )

    clock = {"t": 0.0}
    breaker = CircuitBreaker(
        "chaos-probe", threshold=3, cooldown_s=5.0, now=lambda: clock["t"]
    )

    def failing():
        raise ConnectionError("chaos: injected upstream failure")

    for _ in range(breaker.threshold):
        try:
            breaker.call(failing)
        except ConnectionError:
            pass
    opened = breaker.state == OPEN

    # open: short-circuits without touching the upstream
    upstream_calls = {"n": 0}

    def probe():
        upstream_calls["n"] += 1
        return "ok"

    short_circuited = False
    try:
        breaker.call(probe)
    except BreakerOpenError:
        short_circuited = upstream_calls["n"] == 0

    clock["t"] += breaker.cooldown_s
    half_opened = breaker.state == HALF_OPEN

    # a failed half-open probe re-opens and restarts the cooldown
    try:
        breaker.call(failing)
    except ConnectionError:
        pass
    reopened = breaker.state == OPEN

    clock["t"] += breaker.cooldown_s
    breaker.call(probe)
    closed = breaker.state == "closed" and upstream_calls["n"] == 1

    return {
        "ok": all([opened, short_circuited, half_opened, reopened, closed]),
        "opened_after_threshold": opened,
        "short_circuited": short_circuited,
        "half_opened_after_cooldown": half_opened,
        "reopened_on_probe_failure": reopened,
        "closed_on_probe_success": closed,
        "snapshot": breaker.snapshot(),
    }


# -- pillar 3: tick watchdog + stale-graph degradation -----------------------


def _post(port: int, unique_id: str, timeout_s: float = 30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"uniqueId": unique_id, "lookBack": 30_000, "time": 1_000_000}
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        body = json.loads(resp.read())
        elapsed_ms = (time.perf_counter() - t0) * 1000
        return resp.status, resp.headers, body, elapsed_ms


def pillar_degraded_serve() -> dict:
    from kmamiz_tpu.core import programs
    from kmamiz_tpu.server.dp_server import DataProcessorServer
    from kmamiz_tpu.server.processor import DataProcessor

    window = _clean_groups(10, prefix="dg")
    hang = {"s": 0.0}

    def source(_lb, _t, _lim):
        if hang["s"]:
            time.sleep(hang["s"])
        return window

    processor = DataProcessor(trace_source=source)
    server = DataProcessorServer(processor, host="127.0.0.1", port=0)
    server.start()
    try:
        # warm tick with the watchdog off: its compiles may legitimately
        # exceed any realistic deadline, and the pillar is about what
        # happens AFTER a good tick exists
        os.environ["KMAMIZ_TICK_DEADLINE_MS"] = "0"
        status, _, body, _ = _post(server.port, "chaos-warm")
        warm_ok = status == 200 and not body.get("stale")

        # overrunning tick: the source hangs well past the deadline
        os.environ["KMAMIZ_TICK_DEADLINE_MS"] = "250"
        hang["s"] = 2.0
        snapshot = programs.snapshot()
        status, headers, body, degraded_ms = _post(server.port, "chaos-stale")
        new_compiles = sum(programs.new_compiles_since(snapshot).values())
        stale_ok = (
            status == 200
            and body.get("stale") is True
            and body.get("uniqueId") == "chaos-stale"
            and body.get("staleReason") == "deadline"
            and headers.get("X-KMamiz-Stale-Age-Ms") is not None
        )

        # let the abandoned straggler drain, then prove recovery: with
        # the deadline lifted the next tick serves fresh again
        time.sleep(hang["s"] + 0.5)
        hang["s"] = 0.0
        os.environ["KMAMIZ_TICK_DEADLINE_MS"] = "0"
        status, _, body, _ = _post(server.port, "chaos-recovered")
        recovered_ok = status == 200 and not body.get("stale")
    finally:
        os.environ["KMAMIZ_TICK_DEADLINE_MS"] = "0"
        server.stop()

    return {
        "ok": warm_ok and stale_ok and new_compiles == 0 and recovered_ok,
        "warm_tick": warm_ok,
        "stale_served": stale_ok,
        "stale_new_compiles": new_compiles,
        "recovered_after_straggler": recovered_ok,
        "degraded_serve_ms": round(degraded_ms, 1),
    }


# -- pillar 4: kill -9 mid-ingest -> WAL replay ------------------------------


def run_child_kill() -> None:
    """Crash child (parent sets KMAMIZ_WAL=1 + the WAL dir): ingest all
    windows but the last, WAL the last one, then die before its merge —
    the exact crash point ingest_raw_window's append-before-merge
    ordering exists for. Never returns."""
    chunks = _clean_chunks(prefix="w")
    dp = _fresh_processor()
    for raw in chunks[:-1]:
        dp.ingest_raw_window(raw)
    dp._wal_append(chunks[-1])
    os.kill(os.getpid(), signal.SIGKILL)


def pillar_wal_recovery(seed: int, tmpdir: str) -> dict:
    from kmamiz_tpu.resilience.chaos import graph_signature

    wal_dir = os.path.join(tmpdir, "wal")
    child_env = {
        **os.environ,
        "KMAMIZ_WAL": "1",
        "KMAMIZ_WAL_DIR": wal_dir,
    }
    child = subprocess.run(
        [
            sys.executable,
            os.path.abspath(__file__),
            "--child-kill",
            "--seed",
            str(seed),
        ],
        env=child_env,
        capture_output=True,
        timeout=600,
    )
    killed = child.returncode == -signal.SIGKILL

    chunks = _clean_chunks(prefix="w")

    # reference: every window ingested in-process, WAL off so the
    # recovery dir only holds what the child wrote before dying
    os.environ["KMAMIZ_WAL"] = "0"
    reference = _fresh_processor()
    for raw in chunks:
        reference.ingest_raw_window(raw)
    reference_sig = graph_signature(reference.graph)

    os.environ["KMAMIZ_WAL"] = "1"
    os.environ["KMAMIZ_WAL_DIR"] = wal_dir
    try:
        t0 = time.perf_counter()
        recovered = _fresh_processor()
        replay = recovered.replay_wal()
        recovery_ms = (time.perf_counter() - t0) * 1000
    finally:
        os.environ["KMAMIZ_WAL"] = "0"
    recovered_sig = graph_signature(recovered.graph)

    return {
        "ok": (
            killed
            and replay["replayed"] == len(chunks)
            and recovered_sig == reference_sig
        ),
        "child_sigkilled": killed,
        "wal_records_replayed": replay["replayed"],
        "windows": len(chunks),
        "bitExact": recovered_sig == reference_sig,
        "signature": recovered_sig,
        "chaos_recovery_ms": round(recovery_ms, 1),
    }


# -- driver ------------------------------------------------------------------


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--matrix",
        type=int,
        default=None,
        metavar="N",
        help="run the seed-dependent pillars (quarantine, WAL recovery) "
        "across N consecutive seeds starting at --seed; the seedless "
        "breaker and degraded-serve pillars run once",
    )
    parser.add_argument(
        "--child-kill",
        action="store_true",
        help="internal: crash-child mode for the WAL pillar (never returns)",
    )
    args = parser.parse_args()

    if args.child_kill:
        run_child_kill()
        return 1  # unreachable

    results = {"seed": args.seed}
    with tempfile.TemporaryDirectory(prefix="kmamiz-chaos-") as tmpdir:
        if args.matrix is None:
            results["quarantine"] = pillar_quarantine(args.seed, tmpdir)
            results["breaker"] = pillar_breaker()
            results["degraded_serve"] = pillar_degraded_serve()
            results["wal_recovery"] = pillar_wal_recovery(args.seed, tmpdir)
        else:
            # per-seed tmp subdirs keep quarantine/WAL artifacts apart;
            # the cached quarantine instance is rebound per seed so each
            # iteration's count starts at zero under its own dir
            from kmamiz_tpu.resilience import quarantine as res_quarantine

            seeds = list(range(args.seed, args.seed + max(1, args.matrix)))
            per_seed = []
            for seed in seeds:
                seed_dir = os.path.join(tmpdir, f"seed{seed}")
                os.makedirs(seed_dir, exist_ok=True)
                res_quarantine.reset_for_tests()
                per_seed.append(
                    {
                        "seed": seed,
                        "quarantine": pillar_quarantine(seed, seed_dir),
                        "wal_recovery": pillar_wal_recovery(seed, seed_dir),
                    }
                )
            results["matrix"] = per_seed
            results["matrix_seeds"] = seeds
            # aggregate view: worst case across seeds for the seeded
            # pillars, the seedless pillars once
            results["quarantine"] = {
                "ok": all(r["quarantine"]["ok"] for r in per_seed),
                "seeds_passed": sum(
                    1 for r in per_seed if r["quarantine"]["ok"]
                ),
                "quarantined": sum(
                    r["quarantine"]["quarantined"] for r in per_seed
                ),
            }
            results["breaker"] = pillar_breaker()
            results["degraded_serve"] = pillar_degraded_serve()
            results["wal_recovery"] = {
                "ok": all(r["wal_recovery"]["ok"] for r in per_seed),
                "seeds_passed": sum(
                    1 for r in per_seed if r["wal_recovery"]["ok"]
                ),
                "chaos_recovery_ms": max(
                    r["wal_recovery"]["chaos_recovery_ms"] for r in per_seed
                ),
            }

    pillars = ("quarantine", "breaker", "degraded_serve", "wal_recovery")
    results["ok"] = all(results[p]["ok"] for p in pillars)
    # the two bench.py extras, hoisted to the top level
    results["chaos_recovery_ms"] = results["wal_recovery"]["chaos_recovery_ms"]
    results["degraded_serve_ms"] = results["degraded_serve"][
        "degraded_serve_ms"
    ]

    width = max(len(p) for p in pillars)
    for p in pillars:
        state = "PASS" if results[p]["ok"] else "FAIL"
        detail = {
            k: v
            for k, v in results[p].items()
            if k not in ("ok", "signature", "snapshot", "byReason")
        }
        print(f"{p:<{width}}  {state}  {detail}", file=sys.stderr)

    print(json.dumps(results))
    return 0 if results["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
