"""graftsoak CLI: the thousand-scenario production-replay sweep.

Fans (archetype, seed) cells across worker subprocesses, longest
graftcost-predicted cell first, with a resumable on-disk manifest under
--soak-dir / KMAMIZ_SOAK_DIR: kill it anytime, rerun the same command,
and only the unfinished (plus any failed) cells execute. Every failure
keeps its namespaced flight-*.json box and is auto-triaged against the
archetype's last passing flight (docs/SCENARIOS.md#graftsoak).

stdout carries ONE JSON line with the sweep report plus the bench keys:

    soak_pass              complete + pass rate >= floor + all triaged
    soak_pass_rate         passing fraction of non-poison cells
    soak_triaged_fraction  failures carrying a triage blame (want 1.0)
    soak_cells_per_min     this run's execution throughput

The human-readable report goes to stderr. Exit 0 iff soak_pass.

    python tools/graftsoak.py --cells 200                # the 200-cell gate
    python tools/graftsoak.py --cells 1000 --workers 8   # the real thing
    python tools/graftsoak.py --cells 24 --poison 1      # triage canary
    python tools/graftsoak.py --report-only              # re-render report
    python tools/graftsoak.py --cells 12 --list          # plan, don't run
"""
from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, "/root/repo")

from kmamiz_tpu.soak import (  # noqa: E402
    SoakManifest,
    build_report,
    plan_sweep,
    run_sweep,
)


def _render(report: dict) -> str:
    lines = [
        f"soak: {report['cells_finished']}/{report['cells_total']} cells "
        f"({report['cells_executed']} executed this run, "
        f"{report['cells_per_min']}/min)  "
        f"pass_rate={report['pass_rate']} (floor {report['pass_floor']})  "
        f"triaged={report['triaged_fraction']}  "
        f"{'PASS' if report['soak_pass'] else 'FAIL'}"
    ]
    for bug in report["bugs"]:
        lines.append(
            f"  bug x{bug['count']}: {bug['signature']}  "
            f"cells={','.join(bug['cells'][:4])}"
        )
    for f in report["failures"][:8]:
        flight = f.get("flight_artifact") or "-"
        lines.append(f"  fail {f['id']}: gates={f['gates_failed']}  {flight}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cells", type=int, default=100, help="sweep size")
    ap.add_argument("--seed", type=int, default=0, help="first matrix seed")
    ap.add_argument(
        "--workers", type=int, default=None, help="worker subprocesses"
    )
    ap.add_argument("--ticks", type=int, default=None, help="ticks per cell")
    ap.add_argument(
        "--archetypes",
        default=None,
        help="comma-separated archetype subset (default: sweepable set)",
    )
    ap.add_argument(
        "--poison",
        type=int,
        default=0,
        help="seed N canary cells forced to fail (proves triage fires)",
    )
    ap.add_argument(
        "--soak-dir", default=None, help="sweep directory (KMAMIZ_SOAK_DIR)"
    )
    ap.add_argument(
        "--no-rerun-failed",
        action="store_true",
        help="resume without re-executing already-failed cells",
    )
    ap.add_argument(
        "--report-only",
        action="store_true",
        help="aggregate + print the report from existing records",
    )
    ap.add_argument(
        "--list",
        action="store_true",
        help="write/print the cost-ordered plan without running",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    archetypes = (
        [a.strip() for a in args.archetypes.split(",") if a.strip()]
        if args.archetypes
        else None
    )

    if args.list:
        man = SoakManifest(args.soak_dir)
        doc = plan_sweep(
            man,
            args.cells,
            seed=args.seed,
            archetypes=archetypes,
            ticks=args.ticks,
            poison=args.poison,
        )
        for cell in doc["cells"]:
            print(json.dumps(cell))
        return 0

    if args.report_only:
        report = build_report(SoakManifest(args.soak_dir))
        report.setdefault("cells_executed", 0)
        report.setdefault("cells_per_min", 0.0)
        report.setdefault("wall_s", 0.0)
    else:
        report = run_sweep(
            n_cells=args.cells,
            seed=args.seed,
            workers=args.workers,
            ticks=args.ticks,
            archetypes=archetypes,
            poison=args.poison,
            soak_dir=args.soak_dir,
            rerun_failed=not args.no_rerun_failed,
            verbose=args.verbose,
        )

    print(_render(report), file=sys.stderr)
    print(
        json.dumps(
            {
                **report,
                "soak_pass_rate": report["pass_rate"],
                "soak_triaged_fraction": report["triaged_fraction"],
                "soak_cells_per_min": report["cells_per_min"],
            }
        )
    )
    return 0 if report["soak_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
