"""Dev-box profiling harness for the native span parse.

Times kmamiz_tpu.native.parse_spans on the bench's 1.05M-span synthetic
window across thread counts, printing per-rep walls plus the native phase
breakdown, min and median. No jax import needed (bench.py's module level
is jax-free; make_raw_window is imported from it so the profiled workload
IS the headline workload).
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, "/root/repo")

from bench import make_raw_window  # noqa: E402
from kmamiz_tpu import native as native_mod  # noqa: E402


def main() -> None:
    n_traces = int(sys.argv[1]) if len(sys.argv) > 1 else 150_000
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    threads = [int(t) for t in sys.argv[3].split(",")] if len(sys.argv) > 3 else [1, 2, 4]
    t0 = time.perf_counter()
    # the bench headline's BASELINE workload shape (1k svc / 10 urls
    # each) so the profiled parse IS the headline parse
    raw = make_raw_window(n_traces, 7, n_services=1000, urls_per_service=10)
    print(f"window: {n_traces * 7} spans, {len(raw)/1e6:.1f} MB "
          f"(gen {time.perf_counter()-t0:.1f}s)")
    for T in threads:
        walls, tms = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = native_mod.parse_spans(raw, threads=T)
            walls.append((time.perf_counter() - t0) * 1000)
            if out is None:
                print("native loader unavailable")
                return
            tms.append(out["timings"])
        walls_s = sorted(walls)
        best = walls.index(min(walls))
        tm = tms[best]
        print(
            f"t{T}: min {walls_s[0]:7.1f} ms  med {walls_s[len(walls_s)//2]:7.1f}"
            f"  max {walls_s[-1]:7.1f}  | best rep: prescan {tm['prescan_us']/1000:6.1f}"
            f"  parse {tm['parse_us']/1000:6.1f}  merge {tm['merge_us']/1000:6.1f}"
            f"  (native threads {tm['threads']})"
        )
        print(f"     reps: {[round(w) for w in walls]}")


if __name__ == "__main__":
    main()
