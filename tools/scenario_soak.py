"""Scenario soak: run the seeded scenario matrix against a real server.

Instantiates the archetype matrix from kmamiz_tpu/scenarios/ (one
integer seed composes every topology, traffic curve, and failure
storyline) and drives each scenario closed-loop against an in-process
DataProcessorServer / TickRouter, scoring it on its SLO scorecard —
p50/p95/p99 tick latency, stale-serve rate, lost-span count, quarantine
exactness, recovery-time-to-fresh, zero steady-state recompiles, and a
bit-exact reference-graph replay (docs/SCENARIOS.md).

stdout carries ONE JSON line with the per-scenario scorecards plus the
bench.py headline keys hoisted to the top level:

    scenario_matrix_pass        every scenario passed all its gates
    scenario_worst_p99_tick_ms  max p99 fresh-tick latency across cards
    scenario_worst_recovery_ms  max recovery-to-fresh across cards
    scenario_lost_spans         total lost spans across cards (must be 0)

The human-readable scorecard table goes to stderr. Exit 0 iff the
matrix passes (always 0 with --list). bench.py invokes this as a
subprocess for the scenario extras; tools/slo_report.py gates the
headline keys across rounds.

    python tools/scenario_soak.py --seed 0              # full matrix
    python tools/scenario_soak.py --matrix 3 --ticks 6  # bench subset
    python tools/scenario_soak.py --scenario kill9-wal-replay
    python tools/scenario_soak.py --list                # compose only
    python tools/scenario_soak.py --counterfactual      # graftpilot gate

With --counterfactual the seeded cascade scenario runs twice — control
plane OFF then ON — and the JSON line carries the graftpilot gate keys
instead (``control_counterfactual_prevented``, ``counterfactual_pass``;
docs/CONTROL.md#counterfactual).
"""
from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, "/root/repo")

from kmamiz_tpu.scenarios import (  # noqa: E402
    ARCHETYPES,
    run_counterfactual,
    run_matrix,
    scenario_matrix,
    spec_signature,
)


def headline(cards) -> dict:
    """The always-gated bench keys, hoisted from the scorecards."""
    return {
        "scenario_matrix_pass": bool(cards) and all(c["pass"] for c in cards),
        "scenario_worst_p99_tick_ms": max(
            (c["p99_tick_ms"] for c in cards), default=0.0
        ),
        "scenario_worst_recovery_ms": max(
            (c["recovery_ms"] for c in cards), default=0.0
        ),
        "scenario_lost_spans": sum(c["lost_spans"] for c in cards),
    }


def _table(cards) -> str:
    width = max((len(c["name"]) for c in cards), default=4)
    lines = []
    for c in cards:
        state = "PASS" if c["pass"] else "FAIL"
        fails = [k for k, v in c["gates"].items() if not v]
        lines.append(
            f"{c['name']:<{width}}  {state}  "
            f"p99={c['p99_tick_ms']}ms stale={c['stale_serves']} "
            f"lost={c['lost_spans']} "
            f"q={c['quarantined']}/{c['expected_poisons']} "
            f"recovery={c['recovery_ms']}ms "
            f"recompiles={c['steady_recompiles']} "
            f"wall={c['wall_s']}s{'  ' + str(fails) if fails else ''}"
        )
        if c.get("flight_artifact"):
            # the frozen graftprof evidence for this failure
            # (tools/graftprof.py <path> renders it)
            lines.append(f"{'':<{width}}  flight: {c['flight_artifact']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=None, help="matrix seed")
    ap.add_argument(
        "--matrix", type=int, default=None, help="number of scenarios"
    )
    ap.add_argument("--ticks", type=int, default=None, help="ticks per soak")
    ap.add_argument(
        "--scenario",
        default=None,
        metavar="ARCHETYPE",
        help="run only matrix entries of this archetype "
        f"({', '.join(name for name, _ in ARCHETYPES)})",
    )
    ap.add_argument(
        "--list",
        action="store_true",
        help="compose the matrix and print specs without running",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit nonzero unless every scenario passes its scorecard "
        "(the default; kept explicit for gate invocations)",
    )
    ap.add_argument(
        "--counterfactual",
        action="store_true",
        help="run the graftpilot counterfactual gate (cascade scenario "
        "with the control plane OFF vs ON) instead of the matrix",
    )
    args = ap.parse_args(argv)

    if args.counterfactual:
        card = run_counterfactual(
            seed=args.seed if args.seed is not None else 0,
            n_ticks=args.ticks if args.ticks is not None else 10,
            verbose=True,
        )
        fails = [k for k, v in card["gates"].items() if not v]
        print(
            f"{card['name']}  {'PASS' if card['pass'] else 'FAIL'}  "
            f"prevented={card['slo_violations_prevented']} "
            f"off_violations={card['off']['violations']} "
            f"on_deferred={card['on']['deferred']} "
            f"lost={card['off']['lost_spans']}+{card['on']['lost_spans']} "
            f"wall={card['wall_s']}s"
            f"{'  ' + str(fails) if fails else ''}",
            file=sys.stderr,
        )
        print(
            json.dumps(
                {
                    "counterfactual": card,
                    "control_counterfactual_prevented": card[
                        "slo_violations_prevented"
                    ],
                    "counterfactual_pass": card["pass"],
                }
            )
        )
        return 0 if card["pass"] else 1

    specs = scenario_matrix(args.seed, args.matrix, args.ticks)
    if args.scenario is not None:
        known = {name for name, _ in ARCHETYPES}
        if args.scenario not in known:
            ap.error(f"unknown archetype {args.scenario!r}")
        specs = tuple(s for s in specs if s.archetype == args.scenario)
        if not specs:
            # the archetype exists but the matrix slice missed it: run
            # one instance at its canonical matrix index
            index = next(
                i
                for i, (name, _) in enumerate(ARCHETYPES)
                if name == args.scenario
            )
            specs = (scenario_matrix(args.seed, index + 1, args.ticks)[index],)

    if args.list:
        for spec in specs:
            doc = {
                "name": spec.name,
                "archetype": spec.archetype,
                "n_ticks": spec.n_ticks,
                "tenants": [p.tenant for p in spec.tenants],
                "events": [
                    {"tenant": t, "event": ev.key()}
                    for t, ev in spec.events()
                ],
                "spec_signature": spec_signature(spec),
            }
            print(json.dumps(doc))
        return 0

    cards = run_matrix(specs)
    results = {"scenarios": cards, **headline(cards)}

    print(_table(cards), file=sys.stderr)
    print(json.dumps(results))
    return 0 if results["scenario_matrix_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
