"""SLO scorecard report + regression gate over bench artifacts.

Renders the graftscope scorecard keys (telemetry/slo.py) from bench
result JSON, and — with --check — compares a candidate result against
the latest BENCH_r*.json baseline, exiting nonzero when any
higher-is-worse SLO key regresses beyond the threshold. Runnable as a
tier-1-adjacent gate:

    python tools/slo_report.py                     # render latest artifact
    python tools/slo_report.py --check new.json    # gate new vs latest
    python tools/slo_report.py --check             # gate latest vs previous

Artifact shapes accepted: the driver's {cmd, rc, parsed, tail} wrapper
(parsed dict preferred, else the last JSON line found in tail) or a bare
bench.py result object.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kmamiz_tpu.telemetry.profiling.report import (  # noqa: E402
    DEFAULT_THRESHOLDS as _PROF_THRESHOLDS,
)
from kmamiz_tpu.telemetry.slo import SLO_KEYS_HIGHER_IS_WORSE  # noqa: E402

# bench keys gated alongside the scorecard: the tick-latency headline
# pair, the 100k-endpoint refresh (ROADMAP item 2), the tenancy pair —
# the stacked 8-tenant dispatch latency and the join-compile counter (a
# warm-bucket join must stay at zero compiles) — and the scenario-soak
# headline trio (ISSUE 8: worst p99 tick, worst recovery-to-fresh,
# total lost spans across the always-on matrix)
_EXTRA_GATED = (
    "dp_tick_ms_2500_traces",
    "dp_tick_cached_ms",
    "graph_refresh_ms_100k",
    # worst single merge wall across the 100k-endpoint scale section
    # (ISSUE 13): the segment-append growth path must not trade refresh
    # latency for merge-wall regressions
    "graph_merge_wall_ms_100k",
    "tenant_batched_tick_ms_8",
    "tenant_join_compile_count",
    "scenario_worst_p99_tick_ms",
    "scenario_worst_recovery_ms",
    "scenario_lost_spans",
    # graftprof per-phase attribution p95s (bench always emits them,
    # 0.0 when a phase had no samples) — a per-phase regression fails
    # the round even when the headline tick medians stay flat
    "prof_parse_ms_p95",
    "prof_merge_lockwait_ms_p95",
    "prof_transfer_ms_p95",
    "prof_device_walk_ms_p95",
    # sparse flat-gather walk backend (ISSUE 13): its own phase name so
    # --diff compares walk backends instead of folding both into one
    "prof_device_walk_sparse_ms_p95",
    # STLGT continual-model latency pair (ISSUE 10): the per-fold train
    # tick and the served quantile forward behind /model/forecast
    "stlgt_train_tick_ms",
    "stlgt_infer_ms",
    # graftpilot latency pair (ISSUE 11): the fold-boundary decision
    # recompute and the serving-edge admission read (must stay within
    # 3% of dp_tick — bench asserts the ratio, this gates the drift)
    "control_decision_ms",
    "control_tick_overhead_ms",
    # graftcost crossing pair (ROADMAP item 6): the segment crossing
    # wall on a warm store and the prewarm-ON consolidation stall (the
    # A/B's treated arm — it must stay at steady-merge cost, not drift
    # back toward the OFF arm's compile wall)
    "graph_capacity_grow_ms",
    "capacity_growth_stall_ms",
    # graftstream freshness pair (ISSUE 16): the worst arrival->visible
    # p99 across the burst + diurnal curves (also hard-capped below at
    # _FRESHNESS_CEILING_MS) and the graftprof plane's own p99; the
    # steady-recompile count rides the integer slack (one-compile drift
    # already fails)
    "stream_freshness_ms_p99",
    "prof_freshness_ms_p99",
    "stream_steady_recompiles",
    # graftfleet (ROADMAP item 2 / docs/FLEET.md): spans dropped across
    # the bench's live migration — the drain-queue handoff promises
    # zero, so ANY loss is a regression (integer slack already makes
    # one lost span fail)
    "fleet_migration_lost_spans",
    # graftrace (ISSUE 19 / docs/STATIC_ANALYSIS.md): the concurrency
    # lint pass must stay cheap enough to run pre-merge, and findings
    # must stay at ZERO — integer slack already makes one finding fail
    "graftrace_repo_ms",
    "graftrace_findings",
)
# boolean pass/fail keys: any True -> False flip is a regression (bool
# is an int subclass, so the numeric threshold check would wave a
# True -> False transition through as 1.0 -> 0.0 "improvement")
_BOOL_GATED = (
    "scenario_matrix_pass",
    "graph_refresh_pass",
    # the transfer-guarded warm stream must keep compiling NOTHING
    "stream_zero_recompiles_pass",
    # the bench's fleet migration (drain -> WAL handoff -> replay ->
    # ring flip) must keep landing bit-exact with zero loss
    "fleet_migration_pass",
)
# higher-is-BETTER float floors: the numeric check above only catches
# increases, so a coverage collapse would read as an "improvement".
# stlgt_p99_coverage is a [0,1] calibration rate where relative
# thresholds are meaningless near 1.0 — the gate is absolute: new below
# old minus the slack regresses
_FLOOR_GATED = (
    "stlgt_p99_coverage",
    "control_counterfactual_prevented",
    # predictive-prewarm hit rate over the bench A/B's consolidations:
    # a collapse to cold crossings must fail the round even though the
    # numeric check would read 1.0 -> 0.0 as an improvement
    "cost_prewarm_hit_rate",
    # stream-vs-serial wall ratio: the overlap collapsing back to the
    # serial wall reads as a lower number — gate it as a floor
    "stream_vs_batch_speedup",
    # graftfleet scaling pair: the 4-worker aggregate throughput and
    # its per-worker efficiency vs the 1-worker baseline — a scaling
    # collapse reads as lower numbers, so both gate as floors (and the
    # efficiency also has the candidate-local absolute check below,
    # host-core-guarded)
    "fleet_spans_per_sec_4",
    "fleet_scale_efficiency",
    # graftsoak sweep smoke: the mini-sweep's non-poison pass rate and
    # its triaged fraction (every failure must carry a triage blame) —
    # both [0,1] rates where a collapse reads as a lower number, so
    # both gate as floors
    "soak_smoke_pass_rate",
    "soak_triaged_fraction",
)
_ABS_SLACK_FLOOR = 0.02
# absolute slack per key class: rates jitter in the 3rd decimal on tiny
# denominators, recompile counts are integers, latencies get 0.5 ms
_ABS_SLACK_RATE = 0.005
_ABS_SLACK_COUNT = 1.0
_ABS_SLACK_MS = 0.5
# per-phase relative thresholds for the prof_* keys: shared with
# tools/graftprof.py --diff so a phase regresses at the same bar whether
# gated per-round here or artifact-vs-artifact there. Where a phase has
# its own threshold (merge lock-wait jitters most) it overrides the
# CLI-wide --threshold.
_PROF_KEY_PHASE = {
    "prof_parse_ms_p95": "parse",
    "prof_merge_lockwait_ms_p95": "native-merge-lockwait",
    "prof_transfer_ms_p95": "host-transfer",
    "prof_device_walk_ms_p95": "walk",
    "prof_device_walk_sparse_ms_p95": "walk_sparse",
}
# parse thread-scaling gate (ISSUE 12): the t2 merge regression (a
# shared atomic intern table serializing the merge) showed up as
# wall(t2) >> wall(t1) — 4.3x on BENCH_r03 — long before any p95 moved.
# The expected shape is host-dependent, so the artifact's own
# e2e_host_cores picks the check: with real cores, t1 -> t2 -> t4 wall
# must stay monotone NON-INCREASING within jitter slack; on a 1-core
# box extra threads only timeslice (they cannot speed up), so the gate
# instead bounds every tN wall to a fixed multiple of t1 — catching the
# contention collapse while tolerating scheduler overhead. Both checks
# are candidate-local: no baseline needed, so one bad round can never
# become the new baseline.
_SCALING_KEY = "parse_thread_scaling_1core"
_SCALING_REL_SLACK = 0.15  # best-of-2 walls still jitter on a busy box
_SCALING_ABS_SLACK_MS = 2.0
_SCALING_1CORE_FACTOR = 1.5  # timeslice overhead ceiling vs the t1 wall

# graftstream freshness SLO (ISSUE 16): span-arrival -> forecast-visible
# p99 must stay under this ceiling under the burst + diurnal curves.
# Candidate-local and absolute — a slow creep that stays within the
# relative threshold each round must still fail the moment it crosses.
_FRESHNESS_CEILING_MS = 250.0
_FRESHNESS_KEY = "stream_freshness_ms_p99"


def check_freshness_ceiling(result: dict):
    """Violation strings when the candidate's stream freshness p99
    breaches the absolute SLO ([] when healthy or the key is absent —
    a failed bench section emits None, which the driver flags)."""
    p99 = result.get(_FRESHNESS_KEY)
    if not isinstance(p99, (int, float)) or isinstance(p99, bool):
        return []
    if p99 >= _FRESHNESS_CEILING_MS:
        return [
            f"{_FRESHNESS_KEY} breached the absolute SLO: {p99}ms >= "
            f"{_FRESHNESS_CEILING_MS}ms ceiling"
        ]
    return []


# graftfleet scale-out gate (ROADMAP item 2): 4 workers must hold >= 3x
# the single-worker ingest rate, i.e. per-worker efficiency >= 0.75. The
# expected shape is host-dependent exactly like the parse-scaling gate
# above: 4 worker processes on a 1-core box only timeslice (no speedup
# is physically available), so the absolute floor only arms when the
# artifact's own host-core count could seat the workers. The floor-gated
# baseline comparison above still catches relative collapses everywhere.
_FLEET_EFFICIENCY_KEY = "fleet_scale_efficiency"
_FLEET_EFFICIENCY_FLOOR = 0.75
_FLEET_MIN_CORES = 4


def check_fleet_scale(result: dict):
    """Violation strings when the candidate's fleet efficiency misses
    the absolute scale-out floor ([] when healthy, absent — a skipped
    fleet section emits None — or the host cannot seat 4 workers)."""
    cores = result.get("fleet_host_cores", result.get("e2e_host_cores"))
    if not isinstance(cores, int) or cores < _FLEET_MIN_CORES:
        return []
    eff = result.get(_FLEET_EFFICIENCY_KEY)
    if not isinstance(eff, (int, float)) or isinstance(eff, bool):
        return []
    if eff < _FLEET_EFFICIENCY_FLOOR:
        return [
            f"{_FLEET_EFFICIENCY_KEY} below the scale-out floor on a "
            f"{cores}-core host: {eff} < {_FLEET_EFFICIENCY_FLOOR} "
            f"(4-worker aggregate must hold >= 3x one worker)"
        ]
    return []


def check_thread_scaling(result: dict):
    """Violation strings for pathological parse-scaling walls ([] when
    healthy, absent, or fewer than two thread counts recorded)."""
    scaling = result.get(_SCALING_KEY)
    if not isinstance(scaling, dict):
        return []
    walls = []
    for label, row in scaling.items():
        if not (isinstance(label, str) and label[:1] == "t"):
            continue
        try:
            threads = int(label[1:])
            wall = float(row["wall_ms"])
        except (KeyError, TypeError, ValueError):
            return [f"{_SCALING_KEY}[{label}] is malformed: {row!r}"]
        walls.append((threads, wall))
    walls.sort()
    violations = []
    multicore = result.get("e2e_host_cores", 0) and result["e2e_host_cores"] > 1
    if multicore:
        for (t_lo, w_lo), (t_hi, w_hi) in zip(walls, walls[1:]):
            if w_hi > w_lo * (1.0 + _SCALING_REL_SLACK) + _SCALING_ABS_SLACK_MS:
                violations.append(
                    f"{_SCALING_KEY} not monotone: t{t_hi} wall "
                    f"{w_hi}ms > t{t_lo} wall {w_lo}ms (+"
                    f"{(w_hi - w_lo) / max(w_lo, 1e-9) * 100:.0f}%, slack "
                    f"{_SCALING_REL_SLACK * 100:.0f}% + "
                    f"{_SCALING_ABS_SLACK_MS}ms)"
                )
    elif walls:
        _, w_base = walls[0]
        bound = w_base * _SCALING_1CORE_FACTOR + _SCALING_ABS_SLACK_MS
        for t_hi, w_hi in walls[1:]:
            if w_hi > bound:
                violations.append(
                    f"{_SCALING_KEY} contention blowup on 1-core host: "
                    f"t{t_hi} wall {w_hi}ms > {_SCALING_1CORE_FACTOR}x "
                    f"t{walls[0][0]} wall {w_base}ms + "
                    f"{_SCALING_ABS_SLACK_MS}ms"
                )
    return violations


def gated_keys():
    return (
        ["slo_" + k for k in SLO_KEYS_HIGHER_IS_WORSE]
        + list(_EXTRA_GATED)
        + list(_BOOL_GATED)
        + list(_FLOOR_GATED)
    )


def _abs_slack(key: str) -> float:
    if key.endswith("_rate"):
        return _ABS_SLACK_RATE
    if key.endswith("_count"):
        return _ABS_SLACK_COUNT
    return _ABS_SLACK_MS


def _extract_result(doc: dict):
    """Bench result object from either artifact shape."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc or "tail" in doc:  # driver wrapper
        if isinstance(doc.get("parsed"), dict):
            return doc["parsed"]
        tail = doc.get("tail") or ""
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    return None
        return None
    return doc


def load_result(path: str):
    with open(path) as f:
        return _extract_result(json.load(f))


def find_artifacts(root: str):
    """BENCH_r*.json sorted oldest -> newest by round number."""

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=round_no)


def render(result: dict, label: str) -> str:
    lines = [f"SLO scorecard — {label}"]
    for key in gated_keys():
        val = result.get(key)
        lines.append(f"  {key:28s} {val if val is not None else '(absent)'}")
    return "\n".join(lines)


def check(candidate: dict, baseline: dict, threshold: float):
    """(regressions, compared): each regression is (key, old, new)."""
    regressions, compared = [], []
    for key in gated_keys():
        new, old = candidate.get(key), baseline.get(key)
        if not isinstance(new, (int, float)) or not isinstance(
            old, (int, float)
        ):
            continue  # absent on either side: nothing to gate
        compared.append(key)
        if key in _BOOL_GATED:
            if bool(old) and not bool(new):
                regressions.append((key, old, new))
            continue
        if key in _FLOOR_GATED:
            if new < old - _ABS_SLACK_FLOOR:
                regressions.append((key, old, new))
            continue
        rel = threshold
        phase = _PROF_KEY_PHASE.get(key)
        if phase is not None:
            rel = max(
                rel,
                _PROF_THRESHOLDS.get(phase, _PROF_THRESHOLDS["default"]),
            )
        if new > old * (1.0 + rel) + _abs_slack(key):
            regressions.append((key, old, new))
    return regressions, compared


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--check",
        nargs="?",
        const="",
        default=None,
        metavar="CANDIDATE_JSON",
        help="gate CANDIDATE (default: latest artifact) against the "
        "previous BENCH_r*.json; exit 1 on any SLO regression",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="relative regression threshold (default 0.10 = +10%%)",
    )
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json artifacts",
    )
    args = ap.parse_args(argv)

    artifacts = find_artifacts(args.root)

    def newest_parseable(pool):
        """(result, path, remaining-older-pool) — some rounds' wrappers
        hold only a truncated tail with no JSON line; walk past them."""
        for i in range(len(pool) - 1, -1, -1):
            got = load_result(pool[i])
            if got is not None:
                return got, pool[i], pool[:i]
        return None, None, []

    if args.check is None:
        result, path, _ = newest_parseable(artifacts)
        if result is None:
            print("no parseable BENCH_r*.json artifacts found", file=sys.stderr)
            return 2
        print(render(result, os.path.basename(path)))
        return 0

    # --check: candidate vs the newest parseable artifact strictly before it
    if args.check:
        candidate = load_result(args.check)
        cand_label = args.check
        baseline_pool = artifacts
        if candidate is None:
            print(f"could not parse candidate {cand_label}", file=sys.stderr)
            return 2
    else:
        # gating is strict about the candidate: a null-parsed wrapper is
        # a broken recording, not a skippable round (BENCH_r04/r05 were
        # silently walked past for two PRs) — rerecord it, don't gate
        # around it. Only BASELINE selection may walk past historical
        # unparseable rounds.
        if not artifacts:
            print("no BENCH_r*.json artifacts found", file=sys.stderr)
            return 2
        cand_path = artifacts[-1]
        candidate = load_result(cand_path)
        if candidate is None:
            print(
                f"{os.path.basename(cand_path)}: no parseable bench result "
                '("parsed": null and no JSON line in tail) — re-record the '
                "round with tools/bench_driver.py instead of gating past it",
                file=sys.stderr,
            )
            return 2
        cand_label = os.path.basename(cand_path)
        baseline_pool = artifacts[:-1]
        if not baseline_pool:
            print("need >=2 artifacts for --check without a candidate")
            return 0
    baseline = None
    base_label = None
    for path in reversed(baseline_pool):
        got = load_result(path)
        if got is not None:
            baseline, base_label = got, os.path.basename(path)
            break
    if baseline is None:
        print("no parseable baseline artifact; nothing to gate")
        return 0

    regressions, compared = check(candidate, baseline, args.threshold)
    # candidate-local invariants, gated regardless of baseline overlap
    scaling_violations = check_thread_scaling(candidate)
    scaling_violations += check_freshness_ceiling(candidate)
    scaling_violations += check_fleet_scale(candidate)
    print(render(candidate, cand_label))
    print(f"baseline: {base_label}; compared {len(compared)} key(s)")
    for msg in scaling_violations:
        print(f"REGRESSION {msg}")
    if not compared:
        print("no overlapping SLO keys (baseline predates graftscope)")
        return 1 if scaling_violations else 0
    for key, old, new in regressions:
        print(
            f"REGRESSION {key}: {old} -> {new} "
            f"({(new - old) / max(abs(old), 1e-9) * 100:+.1f}%, "
            f"threshold {args.threshold * 100:.0f}%)"
        )
    if regressions or scaling_violations:
        return 1
    print("all gated SLO keys within threshold")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
