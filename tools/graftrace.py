#!/usr/bin/env python
"""graftrace CLI: whole-repo concurrency analysis for kmamiz_tpu.

    python tools/graftrace.py                 # run the 3 concurrency
                                              # rules, report, exit 0
    python tools/graftrace.py --strict        # exit 1 on any unsuppressed
                                              # finding or reason-less
                                              # suppression (what CI runs)
    python tools/graftrace.py --locks         # lock inventory table
    python tools/graftrace.py --dot           # acquisition-order graph
                                              # as Graphviz DOT
    python tools/graftrace.py --json          # machine-readable output
    python tools/graftrace.py kmamiz_tpu/ops  # lint a subtree
    python tools/graftrace.py --list-rules

The rules (lock-order-cycle, blocking-call-under-lock,
inconsistent-guard) also run inside plain graftlint; this front-end adds
the lock-model views and scopes --strict to concurrency only. Suppress a
finding with `# graftlint: disable=<rule> -- <reason>` on (or directly
above) the flagged line; docs/STATIC_ANALYSIS.md has the catalogue and
the runtime lock-witness (KMAMIZ_LOCK_WITNESS=1) that cross-checks this
model against witnessed acquisition orders.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kmamiz_tpu.analysis import framework  # noqa: E402
from kmamiz_tpu.analysis.concurrency import locks  # noqa: E402

CONCURRENCY_RULES = (
    "lock-order-cycle",
    "blocking-call-under-lock",
    "inconsistent-guard",
)


def _render_locks(model: locks.LockModel) -> str:
    lines = []
    for lid in sorted(model.locks):
        site = model.locks[lid]
        extra = ""
        if site.alias_of:
            extra = f"  (guards {model.canon(lid)})"
        elif lid in model.trylock_only:
            extra = "  (try-lock only)"
        lines.append(
            f"{site.kind:<9} {lid:<60} {site.rel_path}:{site.line}{extra}"
        )
    lines.append(
        f"{len(model.locks)} lock site(s), "
        f"{len(model.edges)} order edge(s), "
        f"{len(model.wide_edge_pairs)} wide pair(s)"
    )
    return "\n".join(lines)


def _render_dot(model: locks.LockModel) -> str:
    """Acquisition-order graph: solid = confident blocking edge (cycle
    detection input), dashed = try-lock edge (excluded from cycles)."""
    out = ["digraph graftrace {", "  rankdir=LR;", '  node [shape=box];']
    names = {}
    for i, lid in enumerate(sorted(model.locks)):
        if model.locks[lid].alias_of:
            continue  # conditions render as their underlying lock
        names[lid] = f"n{i}"
        out.append(f'  n{i} [label="{lid}"];')
    seen = set()
    for e in model.edges:
        src, dst = model.canon(e.src), model.canon(e.dst)
        key = (src, dst, e.blocking)
        if src not in names or dst not in names or key in seen:
            continue
        seen.add(key)
        style = "" if e.blocking and dst not in model.trylock_only else (
            ' [style=dashed]'
        )
        out.append(f"  {names[src]} -> {names[dst]}{style};")
    out.append("}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftrace", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: kmamiz_tpu/)")
    ap.add_argument(
        "--strict",
        action="store_true",
        default=os.environ.get("KMAMIZ_LINT_STRICT", "") not in ("", "0"),
        help="exit 1 on unsuppressed findings or reason-less suppressions",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--locks", action="store_true", help="print the lock inventory"
    )
    ap.add_argument(
        "--dot", action="store_true", help="acquisition-order graph as DOT"
    )
    ap.add_argument(
        "--rules",
        help=f"comma-separated rule subset (default: {','.join(CONCURRENCY_RULES)})",
    )
    ap.add_argument(
        "--verbose", action="store_true", help="also list suppressed findings"
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        all_rules = framework.all_rules()
        for name in CONCURRENCY_RULES:
            print(f"{name}: {all_rules[name].doc}")
        return 0

    if args.locks or args.dot:
        model = locks.repo_model()
        if args.locks:
            print(_render_locks(model))
        if args.dot:
            print(_render_dot(model))
        return 0

    if args.rules:
        rules = [r.strip() for r in args.rules.split(",")]
        unknown = [r for r in rules if r not in CONCURRENCY_RULES]
        if unknown:
            print(
                f"graftrace: not a concurrency rule: {', '.join(unknown)} "
                f"(choose from {', '.join(CONCURRENCY_RULES)})",
                file=sys.stderr,
            )
            return 2
    else:
        rules = list(CONCURRENCY_RULES)
    try:
        result = framework.lint_paths(
            framework.repo_root(), args.paths or None, rules
        )
    except ValueError as exc:
        print(f"graftrace: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(framework.render_json(result))
    else:
        print(framework.render_text(result, verbose=args.verbose))

    if not args.strict:
        return 0
    bad = len(result.findings)
    missing = result.missing_reasons()
    if missing:
        for path, sup in missing:
            print(
                f"graftrace: strict: {path}:{sup.line}: suppression "
                "without a reason (add `-- <why>`)",
                file=sys.stderr,
            )
    return 1 if (bad or missing) else 0


if __name__ == "__main__":
    sys.exit(main())
