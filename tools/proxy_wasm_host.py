"""Strict proxy-wasm host for driving the in-tree Envoy filter binary.

The assembler/interpreter pair (wasm_asm.py / wasm_interp.py) proves the
binary executes; THIS module proves it honors the proxy-wasm ABI the way
a real Envoy host enforces it (VERDICT r3 #3). It models the host-side
contracts the happy-path test harness skipped:

- **Callback-context legality.** Each host import is only callable from
  the callbacks a real host serves it in: buffer reads only during the
  matching body callback (by on_log Envoy has forwarded/freed the body
  buffers), request-header reads from request-headers onward,
  response-header reads from response-headers onward, nothing after
  on_delete. An out-of-context call raises AbiViolation — the
  "interpreter rejects an ABI-violating binary" bar.
- **Chunked body deliveries with Envoy buffering semantics.** Bodies
  arrive in multiple proxy_on_*_body(ctx, chunk_size, end_of_stream)
  calls. If the module returns Pause (1) the delivered bytes stay
  buffered and grow; if it returns Continue (0) on a NON-final chunk the
  buffered bytes are forwarded downstream and are GONE — a later
  proxy_get_buffer_bytes sees only bytes delivered afterwards. A filter
  that fails to pause therefore visibly corrupts its body capture here,
  exactly as it would in production (the reference pauses:
  /root/reference/envoy/wasm/main.go:101-104,125-128).
- **Return-value discipline.** Body/header callbacks must return a
  proxy-wasm Action (0=Continue, 1=Pause); anything else raises.
- **Stream-shape variants.** stream() drives full streams; the caller
  can also drive request-only streams (close with no response) and
  header reads across pauses — on_log + on_delete always fire, as Envoy
  guarantees.

Reference ABI surface: the tetratelabs proxy-wasm Go SDK hostcalls the
reference filter uses (main.go) — proxy_log, proxy_get_header_map_value,
proxy_get_buffer_bytes, proxy_on_memory_allocate.
"""
from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent))
from wasm_interp import Instance, Module  # noqa: E402

ACTION_CONTINUE = 0
ACTION_PAUSE = 1

MAP_REQUEST = 0
MAP_RESPONSE = 2
BUF_REQUEST_BODY = 0
BUF_RESPONSE_BODY = 1

STATUS_OK = 0
STATUS_NOT_FOUND = 1


class AbiViolation(AssertionError):
    """The module broke a proxy-wasm host contract."""


class _StreamState:
    __slots__ = (
        "request_headers",
        "response_headers",
        "req_buffer",
        "resp_buffer",
        "phase",
        "deleted",
    )

    def __init__(self) -> None:
        self.request_headers: Dict[str, str] = {}
        self.response_headers: Dict[str, str] = {}
        self.req_buffer = b""  # bytes currently buffered by the host
        self.resp_buffer = b""
        self.phase = "created"
        self.deleted = False


class StrictHost:
    """Drives the filter binary through a real host's callback protocol,
    enforcing ABI contracts on every host call the module makes."""

    #: which host-visible buffers may be read during which callback
    #: (Envoy serves body buffers only inside the matching body callback;
    #: by on_log they are forwarded/freed)
    _BUFFER_LEGAL = {
        BUF_REQUEST_BODY: {"on_request_body"},
        BUF_RESPONSE_BODY: {"on_response_body"},
    }
    #: earliest phase (inclusive, in _PHASES order) a header map exists
    _PHASES = (
        "created",
        "request_headers",
        "request_body",
        "response_headers",
        "response_body",
        "log",
        "done",
    )
    _MAP_EARLIEST = {MAP_REQUEST: "request_headers", MAP_RESPONSE: "response_headers"}

    def __init__(self, binary: bytes) -> None:
        self.module = Module(binary)
        self.logs: List[Tuple[int, str]] = []
        self.streams: Dict[int, _StreamState] = {}
        self._active_ctx: Optional[int] = None
        self._active_callback: Optional[str] = None
        self.instance = Instance(
            self.module,
            {
                "env.proxy_log": self._proxy_log,
                "env.proxy_get_header_map_value": self._get_header,
                "env.proxy_get_buffer_bytes": self._get_buffer,
            },
        )

    # -- host imports (contract-checked) -------------------------------------

    def _require_callback(self, what: str) -> _StreamState:
        if self._active_callback is None or self._active_ctx is None:
            raise AbiViolation(f"{what} called outside any stream callback")
        state = self.streams[self._active_ctx]
        if state.deleted:
            raise AbiViolation(f"{what} called on deleted context")
        return state

    def _proxy_log(self, inst, level, ptr, size):
        # legal from any callback (incl. root-context ones); only the
        # memory range is checked (Instance.read bounds-checks)
        self.logs.append((level, inst.read(ptr, size).decode()))
        return STATUS_OK

    def _get_header(self, inst, map_type, kptr, klen, out_ptr, out_size):
        state = self._require_callback("proxy_get_header_map_value")
        if map_type not in self._MAP_EARLIEST:
            raise AbiViolation(f"unknown header map type {map_type}")
        earliest = self._PHASES.index(self._MAP_EARLIEST[map_type])
        if self._PHASES.index(state.phase) < earliest:
            raise AbiViolation(
                f"header map {map_type} read during {state.phase!r}, "
                f"which precedes its existence"
            )
        key = inst.read(kptr, klen).decode()
        hmap = (
            state.request_headers
            if map_type == MAP_REQUEST
            else state.response_headers
        )
        if key not in hmap:
            return STATUS_NOT_FOUND
        return self._deliver(inst, str(hmap[key]).encode(), out_ptr, out_size)

    def _get_buffer(self, inst, buf_type, start, length, out_ptr, out_size):
        state = self._require_callback("proxy_get_buffer_bytes")
        legal = self._BUFFER_LEGAL.get(buf_type)
        if legal is None:
            raise AbiViolation(f"unknown buffer type {buf_type}")
        if self._active_callback not in legal:
            raise AbiViolation(
                f"buffer {buf_type} read during {self._active_callback!r}; "
                f"legal callbacks: {sorted(legal)}"
            )
        data = (
            state.req_buffer
            if buf_type == BUF_REQUEST_BODY
            else state.resp_buffer
        )
        data = data[start : start + length]  # Envoy clamps to available
        if not data:
            return STATUS_NOT_FOUND
        return self._deliver(inst, data, out_ptr, out_size)

    def _deliver(self, inst, payload: bytes, out_ptr: int, out_size: int):
        addr = inst.invoke("proxy_on_memory_allocate", len(payload))[0]
        if addr == 0:
            return STATUS_NOT_FOUND  # module refused the allocation
        inst.write(addr, payload)
        inst.write_u32(out_ptr, addr)
        inst.write_u32(out_size, len(payload))
        return STATUS_OK

    # -- callback driver ------------------------------------------------------

    def _enter(self, ctx: int, callback: str):
        if self._active_callback is not None:
            raise AbiViolation("host reentered while a callback is active")
        self._active_ctx, self._active_callback = ctx, callback

    def _exit(self):
        self._active_ctx = self._active_callback = None

    def _invoke(self, name: str, ctx: int, callback: str, *args) -> List[int]:
        self._enter(ctx, callback)
        try:
            return self.instance.invoke(name, ctx, *args)
        finally:
            self._exit()

    def _action(self, result: List[int], name: str) -> int:
        if len(result) != 1 or result[0] not in (ACTION_CONTINUE, ACTION_PAUSE):
            raise AbiViolation(f"{name} returned non-Action {result!r}")
        return result[0]

    def context_create(self, ctx: int, root: int = 1) -> None:
        state = _StreamState()
        self.streams[ctx] = state
        self._invoke("proxy_on_context_create", ctx, "on_context_create", root)

    def request_headers(self, ctx: int, headers: Dict[str, str]) -> int:
        state = self.streams[ctx]
        state.request_headers = dict(headers)
        state.phase = "request_headers"
        out = self._invoke(
            "proxy_on_request_headers", ctx, "on_request_headers", 0, 0
        )
        return self._action(out, "proxy_on_request_headers")

    def response_headers(self, ctx: int, headers: Dict[str, str]) -> int:
        state = self.streams[ctx]
        state.response_headers = dict(headers)
        state.phase = "response_headers"
        out = self._invoke(
            "proxy_on_response_headers", ctx, "on_response_headers", 0, 0
        )
        return self._action(out, "proxy_on_response_headers")

    def _body(self, ctx, data, chunks, end_stream, is_response) -> List[int]:
        """Deliver `data` in `chunks` pieces with Envoy's buffering
        semantics; returns per-delivery module actions."""
        state = self.streams[ctx]
        state.phase = "response_body" if is_response else "request_body"
        callback = "on_response_body" if is_response else "on_request_body"
        export = (
            "proxy_on_response_body" if is_response else "proxy_on_request_body"
        )
        n = max(1, int(chunks))
        per = (len(data) + n - 1) // n if data else 0
        pieces = (
            [data[i : i + per] for i in range(0, len(data), per)]
            if per
            else [b""]
        )
        actions = []
        for i, piece in enumerate(pieces):
            final = end_stream and i == len(pieces) - 1
            if is_response:
                state.resp_buffer += piece
            else:
                state.req_buffer += piece
            out = self._invoke(export, ctx, callback, len(piece), int(final))
            action = self._action(out, export)
            actions.append(action)
            if action == ACTION_CONTINUE and not final:
                # forwarded downstream: buffered bytes are gone (this is
                # what breaks filters that fail to Pause)
                if is_response:
                    state.resp_buffer = b""
                else:
                    state.req_buffer = b""
        return actions

    def request_body(self, ctx, data: bytes, chunks=1, end_stream=True):
        return self._body(ctx, data, chunks, end_stream, is_response=False)

    def response_body(self, ctx, data: bytes, chunks=1, end_stream=True):
        return self._body(ctx, data, chunks, end_stream, is_response=True)

    def log(self, ctx: int) -> None:
        self.streams[ctx].phase = "log"
        self._invoke("proxy_on_log", ctx, "on_log")

    def done(self, ctx: int) -> None:
        self.streams[ctx].phase = "done"
        self._invoke("proxy_on_done", ctx, "on_done")

    def delete(self, ctx: int) -> None:
        self._invoke("proxy_on_delete", ctx, "on_delete")
        self.streams[ctx].deleted = True

    # -- full-stream conveniences ---------------------------------------------

    def stream(
        self,
        ctx: int,
        request_headers: Dict[str, str],
        response_headers: Optional[Dict[str, str]] = None,
        request_body: Optional[bytes] = None,
        response_body: Optional[bytes] = None,
        body_chunks: int = 1,
    ) -> None:
        """One HTTP stream, Envoy callback order. response_headers=None
        models a stream closed with no response (reset/timeout): Envoy
        still fires on_log + on_delete."""
        self.context_create(ctx)
        self.request_headers(ctx, request_headers)
        if request_body is not None:
            self.request_body(ctx, request_body, chunks=body_chunks)
        if response_headers is not None:
            self.response_headers(ctx, response_headers)
            if response_body is not None:
                self.response_body(ctx, response_body, chunks=body_chunks)
        # proxy-wasm teardown order: done -> log -> delete
        self.done(ctx)
        self.log(ctx)
        self.delete(ctx)
