"""Model-head evaluation at BASELINE.json scale (VERDICT r2 #5).

Generates a layered MicroViSim mesh (default 100 services / 1k endpoints,
BASELINE config 3) with a rich programmatic fault schedule — recurring
nightly windows, overlapping multi-endpoint incidents, probabilistic
windows, and gateway traffic bursts that push services into overload —
then trains/evaluates the GraphSAGE and GAT heads against the
persistence skyline and naive baselines.

Beyond thresholded P/R/F1 this reports threshold-free ROC-AUC and PR-AUC
and ONSET recall: the fraction of fault-window FIRST slots (next slot
anomalous, current slot clean) the model flags. Persistence scores 0
there by construction — onset detection is precisely what a forecaster
adds over "alert when it's already broken".

Usage:
  JAX_PLATFORMS=cpu python tools/eval_models_large.py            # 1k ep
  JAX_PLATFORMS=cpu python tools/eval_models_large.py --services 10
  JAX_PLATFORMS=cpu python tools/eval_models_large.py --tenk     # wall-clock
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from eval_models import _force_cpu  # noqa: E402

_force_cpu()

import numpy as np  # noqa: E402
import yaml  # noqa: E402

TRAIN_FRACTION = 0.75  # the one split definition, passed everywhere


def make_mesh_config(
    n_services: int,
    eps_per_service: int,
    days: int,
    rng: np.random.Generator,
    fault_fraction: float = 0.08,
) -> str:
    """Layered mesh: gateway tier (external traffic) -> mid tiers -> leaf
    tier; each endpoint depends on 1-3 endpoints one tier deeper."""
    n_gw = max(1, n_services // 20)
    n_leaf = max(1, int(n_services * 0.3))
    n_mid = max(1, n_services - n_gw - n_leaf)
    tiers = (
        [0] * n_gw + [1] * (n_mid // 2) + [2] * (n_mid - n_mid // 2) + [3] * n_leaf
    )

    services = []
    ep_ids: list[list[str]] = [[] for _ in range(4)]
    for s in range(n_services):
        tier = tiers[s]
        endpoints = []
        for e in range(eps_per_service):
            eid = f"s{s}-e{e}"
            ep_ids[tier].append(eid)
            endpoints.append(
                {
                    "endpointId": eid,
                    "endpointInfo": {
                        "path": f"/api/s{s}/op{e}",
                        "method": "post" if e % 3 == 0 else "get",
                    },
                }
            )
        services.append(
            {
                "serviceName": f"svc{s}",
                "versions": [
                    {
                        "version": "v1",
                        "replica": int(rng.integers(1, 4)),
                        "endpoints": endpoints,
                    }
                ],
            }
        )

    dependencies = []
    for tier in range(4):
        deeper = ep_ids[tier + 1] if tier < 3 else []
        for eid in ep_ids[tier]:
            entry: dict = {"endpointId": eid}
            if tier == 0:
                entry["isExternal"] = True
            if deeper:
                k = int(rng.integers(1, min(3, len(deeper)) + 1))
                picks = rng.choice(len(deeper), size=k, replace=False)
                entry["dependOn"] = [
                    {"endpointId": deeper[int(p)]} for p in picks
                ]
            if "dependOn" in entry or entry.get("isExternal"):
                dependencies.append(entry)

    tier_latency = [25, 15, 10, 5]
    endpoint_metrics = []
    for tier in range(4):
        for eid in ep_ids[tier]:
            m = {
                "endpointId": eid,
                "delay": {
                    "latencyMs": tier_latency[tier] + int(rng.integers(0, 6)),
                    "jitterMs": 2 + int(rng.integers(0, 4)),
                },
                "errorRatePercent": 1,
            }
            if tier == 0:
                m["expectedExternalDailyRequestCount"] = 4800
            endpoint_metrics.append(m)

    # -- fault schedule -------------------------------------------------------
    all_eps = [e for t in ep_ids for e in t]
    n_faulty = max(3, int(len(all_eps) * fault_fraction))
    faulty = [all_eps[int(i)] for i in rng.choice(len(all_eps), n_faulty, False)]
    third = max(1, n_faulty // 3)
    faults = []

    def window(day, hour, dur, prob=100):
        return {
            "startTime": {"day": day, "hour": hour},
            "durationHours": dur,
            "probabilityPercent": prob,
        }

    # (a) recurring nightly error windows — periodic, learnable, invisible
    # to persistence at onset
    for eid in faulty[:third]:
        hour = int(rng.integers(1, 20))
        faults.append(
            {
                "type": "increase-error-rate",
                "targets": {"services": [], "endpoints": [{"endpointId": eid}]},
                "timePeriods": [window(d, hour, 3) for d in range(1, days + 1)],
                "increaseErrorRatePercent": int(rng.integers(50, 85)),
            }
        )
    # (b) overlapping multi-endpoint incidents: one window, several
    # endpoints at once (correlated failures along the graph)
    incident_eps = faulty[third : 2 * third]
    for i in range(0, len(incident_eps), 3):
        group = incident_eps[i : i + 3]
        day = int(rng.integers(1, days + 1))
        hour = int(rng.integers(0, 20))
        faults.append(
            {
                "type": "increase-error-rate",
                "targets": {
                    "services": [],
                    "endpoints": [{"endpointId": e} for e in group],
                },
                "timePeriods": [window(day, hour, int(rng.integers(2, 5)))],
                "increaseErrorRatePercent": int(rng.integers(50, 80)),
            }
        )
    # (c) probabilistic recurring latency faults (drifting severity)
    for eid in faulty[2 * third :]:
        hour = int(rng.integers(0, 20))
        faults.append(
            {
                "type": "increase-latency",
                "targets": {"services": [], "endpoints": [{"endpointId": eid}]},
                "timePeriods": [
                    window(d, hour, 2, prob=70) for d in range(1, days + 1)
                ],
                "increaseLatencyMs": int(rng.integers(150, 400)),
            }
        )
    # (d) gateway traffic bursts -> overload errors downstream
    for eid in ep_ids[0][: max(1, len(ep_ids[0]) // 4)]:
        day = int(rng.integers(1, days + 1))
        faults.append(
            {
                "type": "inject-traffic",
                "targets": {"services": [], "endpoints": [{"endpointId": eid}]},
                "timePeriods": [window(day, int(rng.integers(8, 16)), 2)],
                "increaseRequestCount": 4000,
            }
        )

    config = {
        "servicesInfo": [{"namespace": "mesh", "services": services}],
        "endpointDependencies": dependencies,
        "loadSimulation": {
            "config": {
                "simulationDurationInDays": days,
                "overloadErrorRateIncreaseFactor": 3,
            },
            "serviceMetrics": [],
            "endpointMetrics": endpoint_metrics,
            "faultInjection": faults,
        },
    }
    return yaml.safe_dump(config, sort_keys=False)


# -- threshold-free + onset metrics -----------------------------------------


def collect_scores(params, dataset, model):
    import jax

    probs, truths, onsets, currents = [], [], [], []
    for i in range(len(dataset.features)):
        _lat, logit = model.forward(
            params,
            dataset.features[i],
            dataset.src,
            dataset.dst,
            dataset.edge_mask,
        )
        mask = np.asarray(dataset.node_mask[i]).astype(bool)
        prob = np.asarray(jax.nn.sigmoid(logit))
        truth = np.asarray(dataset.target_anomaly[i]).astype(bool)
        # onset: the predicted slot is anomalous while the CURRENT slot is
        # still clean (feature col 2 = current 5xx share)
        from kmamiz_tpu.models.trainer import ANOMALY_ERROR_SHARE  # noqa: PLC0415 (jax deferred)

        current_bad = np.asarray(dataset.features[i])[:, 2] > ANOMALY_ERROR_SHARE
        probs.append(prob[mask])
        truths.append(truth[mask])
        onsets.append((truth & ~current_bad)[mask])
        currents.append(current_bad[mask])
    return (
        np.concatenate(probs),
        np.concatenate(truths),
        np.concatenate(onsets),
        np.concatenate(currents),
    )


def roc_auc(scores: np.ndarray, labels: np.ndarray) -> float:
    pos = scores[labels]
    neg = scores[~labels]
    if not len(pos) or not len(neg):
        return float("nan")
    # midranks for ties
    allv = np.concatenate([pos, neg])
    sorted_v = np.sort(allv)
    uniq, first = np.unique(sorted_v, return_index=True)
    counts = np.diff(np.append(first, len(sorted_v)))
    mid = {v: f + (c + 1) / 2 for v, f, c in zip(uniq, first, counts)}
    r_pos = np.array([mid[v] for v in pos])
    u = r_pos.sum() - len(pos) * (len(pos) + 1) / 2
    return float(u / (len(pos) * len(neg)))


def pr_auc(scores: np.ndarray, labels: np.ndarray) -> float:
    if not labels.any():
        return float("nan")
    order = np.argsort(-scores, kind="mergesort")
    sorted_labels = labels[order]
    tp = np.cumsum(sorted_labels)
    precision = tp / np.arange(1, len(sorted_labels) + 1)
    recall = tp / labels.sum()
    # average precision (step-wise integral)
    return float(np.sum(precision[sorted_labels.astype(bool)]) / labels.sum())


def onset_recall(scores, truths, onsets, threshold) -> float:
    n_onset = int(onsets.sum())
    if not n_onset:
        return float("nan")
    return float(((scores > threshold) & onsets).sum() / n_onset)


def _persistence_rows(eval_set):
    """(scores, truths, onsets) for the threshold-free persistence
    skyline over an eval set (node_mask-respecting)."""
    from kmamiz_tpu.models.trainer import ANOMALY_ERROR_SHARE

    p_scores, p_truths, p_onsets = [], [], []
    for i in range(len(eval_set.features)):
        mask = np.asarray(eval_set.node_mask[i]).astype(bool)
        feats = np.asarray(eval_set.features[i])
        truth = np.asarray(eval_set.target_anomaly[i]).astype(bool)
        current_bad = feats[:, 2] > ANOMALY_ERROR_SHARE
        p_scores.append(feats[:, 2][mask])
        p_truths.append(truth[mask])
        p_onsets.append((truth & ~current_bad)[mask])
    return (
        np.concatenate(p_scores),
        np.concatenate(p_truths),
        np.concatenate(p_onsets),
    )


def _hybrid_row(name, metrics, scores, truths, onsets, currents, threshold,
                train_s):
    """persistence ("already broken") UNION the head's forecast ("about
    to break") — the operational pager policy; it can only add the
    model's true onsets (plus its false alarms) on top of the skyline."""
    from kmamiz_tpu.models import trainer

    hybrid = (scores > threshold) | currents
    tp = int((hybrid & truths).sum())
    fp = int((hybrid & ~truths).sum())
    fn = int((~hybrid & truths).sum())
    hp = tp / max(tp + fp, 1)
    hr = tp / max(tp + fn, 1)
    hybrid_metrics = trainer.EvalResult(
        latency_mse=metrics.latency_mse,
        anomaly_accuracy=0.0,
        anomaly_precision=hp,
        anomaly_recall=hr,
        anomaly_base_rate=metrics.anomaly_base_rate,
        per_slot_flagged={},
        anomaly_f1=2 * hp * hr / (hp + hr) if hp + hr else 0.0,
        latency_mae_ms=metrics.latency_mae_ms,
    )
    return (
        f"{name} + persistence (hybrid)",
        hybrid_metrics,
        float("nan"),
        float("nan"),
        onset_recall(scores, truths, onsets, threshold),
        train_s,
    )


def _print_rows(rows) -> None:
    print(
        "| model | precision | recall | F1 | ROC-AUC | PR-AUC | "
        "onset recall | latency MAE (ms) | train wall (s) |"
    )
    print("|---|---|---|---|---|---|---|---|---|")
    for name, m, auc, ap, onset, wall in rows:
        print(
            f"| {name} | {m.anomaly_precision:.3f} | {m.anomaly_recall:.3f} "
            f"| {m.anomaly_f1:.3f} | {auc:.3f} | {ap:.3f} | {onset:.3f} "
            f"| {m.latency_mae_ms:.2f} | {wall:.0f} |"
        )


def inductive_eval(args, result) -> None:
    """Held-out-ENDPOINT evaluation (VERDICT r3 #4): 20% of endpoints
    never contribute a training loss or calibration label; anomaly
    metrics are computed on exactly those endpoints over the held-out
    slots. No node-identity embeddings — the identity signal is replaced
    by causal history features (models/history.py), which transfer to
    endpoints the model never saw."""
    from kmamiz_tpu.models import graphsage, history, trainer
    from kmamiz_tpu.models.trainer import ANOMALY_ERROR_SHARE

    dataset = trainer.dataset_from_simulation(
        result.endpoint_dependencies,
        result.realtime_data_per_slot,
        result.replica_counts,
    )
    held = history.split_endpoints(dataset.num_nodes, 0.2, seed=args.seed)
    print(
        f"\n== INDUCTIVE protocol: {int(held.sum())}/{dataset.num_nodes} "
        "endpoints held out of training losses AND threshold calibration; "
        "metrics below are on those endpoints only, held-out slots only =="
    )

    rows = []
    eval_held = None  # the history iteration's holdout, reused below
    held_slot_count = 0
    for label, use_history in (("with history features", True),
                               ("ablation: base features", False)):
        ds = history.augment_with_history(dataset) if use_history else dataset
        train_set, eval_set = trainer.temporal_split(ds, TRAIN_FRACTION)
        train_seen = history.mask_endpoints(train_set, ~held)
        it_eval_held = history.mask_endpoints(eval_set, held)

        t1 = time.perf_counter()
        res = trainer.train(
            train_seen,
            epochs=args.epochs,
            hidden=args.hidden,
            seed=args.seed,
            model=graphsage,
            use_node_embeddings=False,
        )
        train_s = time.perf_counter() - t1
        if use_history and getattr(args, "checkpoint_dir", None):
            # save AFTER training (never pass checkpoint_dir into
            # trainer.train here: its resume path validates only hypers,
            # so a stale checkpoint from a different mesh would silently
            # skip training and report bogus "fresh" metrics)
            from kmamiz_tpu.models import checkpoint as ckpt

            ckpt.save_checkpoint(
                args.checkpoint_dir,
                res.params,
                # serving restores against optimizer.init(template); the
                # optimizer state itself is not reused, so a fresh init
                # keeps the document shape without threading it out of
                # TrainResult
                graphsage.make_optimizer(0.01).init(res.params),
                step=args.epochs,
                metadata={
                    "loss": float(res.losses[-1]) if res.losses else None,
                    "hidden": args.hidden,
                    "lr": 0.01,
                    "seed": args.seed,
                    "model": "graphsage",
                    "num_features": int(
                        np.asarray(train_seen.features[0]).shape[1]
                    ),
                    "num_nodes": 0,
                },
            )
        threshold = trainer.calibrate_threshold(
            res.params, train_seen, model=graphsage
        )
        metrics = trainer.evaluate(
            res.params, it_eval_held, threshold=threshold, model=graphsage
        )
        scores, truths, onsets, currents = collect_scores(
            res.params, it_eval_held, graphsage
        )
        rows.append(
            (
                f"GraphSAGE ({label})",
                metrics,
                roc_auc(scores, truths),
                pr_auc(scores, truths),
                onset_recall(scores, truths, onsets, threshold),
                train_s,
            )
        )
        if use_history:
            eval_held = it_eval_held
            held_slot_count = len(eval_set.features)
            rows.append(
                _hybrid_row(
                    "GraphSAGE", metrics, scores, truths, onsets,
                    currents, threshold, train_s,
                )
            )

    # the skyline on the SAME held-out endpoints/slots (the skyline only
    # reads base feature columns, which augmentation leaves in place)
    p_scores, p_truths, p_onsets = _persistence_rows(eval_held)
    persist = trainer.evaluate_baseline(eval_held)
    rows.append(
        (
            "persistence skyline (held-out endpoints)",
            persist,
            roc_auc(p_scores, p_truths),
            pr_auc(p_scores, p_truths),
            onset_recall(p_scores, p_truths, p_onsets, ANOMALY_ERROR_SHARE),
            0.0,
        )
    )
    base_rate = rows[0][1].anomaly_base_rate
    rows.append(
        (
            "naive: random @ base rate",
            trainer.evaluate_naive(eval_held, rate=base_rate, seed=args.seed),
            0.5,
            float(p_truths.mean()),
            float(base_rate),
            0.0,
        )
    )
    print(
        f"\nheld-out slots: {held_slot_count}, held-out endpoints: "
        f"{int(held.sum())}, anomaly base rate {base_rate:.3f}, onset "
        f"samples {int(p_onsets.sum())}, epochs {args.epochs}, "
        f"seed {args.seed}\n"
    )
    _print_rows(rows)
    import resource

    peak_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    print(f"peak host memory: {peak_gb:.1f} GB (ru_maxrss)")
    if getattr(args, "checkpoint_dir", None):
        print(f"checkpoint (with-history model): {args.checkpoint_dir}")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--services", type=int, default=100)
    parser.add_argument("--eps-per-service", type=int, default=10)
    parser.add_argument("--days", type=int, default=4)
    parser.add_argument("--epochs", type=int, default=40)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--hidden", type=int, default=32)
    parser.add_argument(
        "--embeddings",
        action="store_true",
        help="learned per-node identity embeddings (MODELS.md future work)",
    )
    parser.add_argument(
        "--inductive",
        action="store_true",
        help="hold out 20%% of ENDPOINTS from training + calibration and "
        "score only them (history features, no identity embeddings)",
    )
    parser.add_argument(
        "--tenk",
        action="store_true",
        help="also time (not score) the 1k-svc/10k-endpoint config",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save the (with-history) inductive model's checkpoint here",
    )
    args = parser.parse_args()

    from kmamiz_tpu.models import gat, graphsage, trainer
    from kmamiz_tpu.simulator.simulator import Simulator

    rng = np.random.default_rng(args.seed)
    t0 = time.perf_counter()
    yaml_cfg = make_mesh_config(
        args.services, args.eps_per_service, args.days, rng
    )
    result = Simulator().generate_simulation_data(
        yaml_cfg, 0.0, rng=np.random.default_rng(args.seed)
    )
    assert result.validation_error_message == "", result.validation_error_message
    assert result.converting_error_message == "", result.converting_error_message
    sim_s = time.perf_counter() - t0
    n_eps = args.services * args.eps_per_service
    print(
        f"mesh: {args.services} services / {n_eps} endpoints / "
        f"{args.days} days -> simulated in {sim_s:.1f}s"
    )

    if args.inductive:
        inductive_eval(args, result)
        return

    rows = []
    shared_dataset = None
    suffix = " (+node embeddings)" if args.embeddings else ""
    for name, model in (
        (f"GraphSAGE{suffix}", graphsage),
        (f"GAT{suffix}", gat),
    ):
        t1 = time.perf_counter()
        res, metrics, dataset = trainer.train_on_simulation(
            result.endpoint_dependencies,
            result.realtime_data_per_slot,
            result.replica_counts,
            train_fraction=TRAIN_FRACTION,
            epochs=args.epochs,
            hidden=args.hidden,
            seed=args.seed,
            model=model,
            use_node_embeddings=args.embeddings,
        )
        train_s = time.perf_counter() - t1
        shared_dataset = dataset
        _train, eval_set = trainer.temporal_split(dataset, TRAIN_FRACTION)
        scores, truths, onsets, currents = collect_scores(
            res.params, eval_set, model
        )
        rows.append(
            (
                name,
                metrics,
                roc_auc(scores, truths),
                pr_auc(scores, truths),
                onset_recall(scores, truths, onsets, metrics.threshold),
                train_s,
            )
        )
        rows.append(
            _hybrid_row(
                name, metrics, scores, truths, onsets, currents,
                metrics.threshold, train_s,
            )
        )

    _train, eval_set = trainer.temporal_split(shared_dataset, TRAIN_FRACTION)
    base_rate = rows[0][1].anomaly_base_rate
    # persistence scores: current 5xx share as the ranking score — the
    # fair threshold-free form of the skyline
    from kmamiz_tpu.models.trainer import ANOMALY_ERROR_SHARE

    p_scores, p_truths, p_onsets = _persistence_rows(eval_set)

    persist = trainer.evaluate_baseline(eval_set)
    rows.append(
        (
            "persistence skyline",
            persist,
            roc_auc(p_scores, p_truths),
            pr_auc(p_scores, p_truths),
            onset_recall(p_scores, p_truths, p_onsets, ANOMALY_ERROR_SHARE),
            0.0,
        )
    )
    rows.append(
        (
            "naive: random @ base rate",
            trainer.evaluate_naive(eval_set, rate=base_rate, seed=args.seed),
            0.5,
            float(p_truths.mean()),
            float(base_rate),
            0.0,
        )
    )

    n_onsets = int(p_onsets.sum())
    print(
        f"\nheld-out slots: {len(eval_set.features)} "
        f"(of {len(shared_dataset.features)}), anomaly base rate "
        f"{base_rate:.3f}, onset samples {n_onsets}, epochs {args.epochs}, "
        f"seed {args.seed}\n"
    )
    _print_rows(rows)

    if args.tenk:
        t2 = time.perf_counter()
        yaml_10k = make_mesh_config(1000, 10, 1, rng)
        r10k = Simulator().generate_simulation_data(
            yaml_10k, 0.0, rng=np.random.default_rng(args.seed)
        )
        assert r10k.validation_error_message == "", r10k.validation_error_message
        assert r10k.converting_error_message == "", r10k.converting_error_message
        gen_s = time.perf_counter() - t2
        t3 = time.perf_counter()
        trainer.train_on_simulation(
            r10k.endpoint_dependencies,
            r10k.realtime_data_per_slot,
            r10k.replica_counts,
            epochs=1,
            hidden=args.hidden,
            seed=args.seed,
            model=graphsage,
            use_node_embeddings=args.embeddings,
        )
        step_s = time.perf_counter() - t3
        print(
            f"\n10k-endpoint wall-clock (BASELINE config 4 shape, 1 day): "
            f"simulate {gen_s:.1f}s, 1-epoch GraphSAGE train+eval {step_s:.1f}s "
            f"(single CPU core; the TPU path trains the same jitted step)"
        )


if __name__ == "__main__":
    main()
