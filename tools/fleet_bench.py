"""Fleet scale-out bench: 1-worker vs 4-worker ingest + live migration.

Boots real subprocess workers (``python -m kmamiz_tpu.fleet.worker``,
each a full DataProcessorServer with its own WAL directory), measures
single-worker ingest throughput, then aggregate throughput with four
workers driven concurrently through ``HTTPTransport``, and finally runs
one live tenant migration (drain -> WAL handoff -> replay -> ring flip)
with a frame injected mid-handoff. Prints ONE json line:

    {"fleet_spans_per_sec_1": ..., "fleet_spans_per_sec_4": ...,
     "fleet_scale_efficiency": ..., "fleet_migration_lost_spans": ...,
     "fleet_migration_pass": ..., "fleet_host_cores": ...}

``fleet_scale_efficiency`` is per-worker: rate4 / (4 * rate1). On a
multi-core host the ROADMAP scale-out target is efficiency >= 0.75
(aggregate >= 3x one worker); on a 1-core host four worker processes
only timeslice, so tools/slo_report.py's absolute floor stays disarmed
(the artifact carries ``fleet_host_cores`` for exactly that guard).

Run by bench.py's fleet section (KMAMIZ_BENCH_FLEET=0 skips there);
standalone: ``python tools/fleet_bench.py [--frames N] [--spawn-s S]``.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kmamiz_tpu.fleet import migration as migration_mod  # noqa: E402
from kmamiz_tpu.fleet.coordinator import (  # noqa: E402
    FleetCoordinator,
    HTTPTransport,
)
from kmamiz_tpu.fleet.ring import HashRing  # noqa: E402
from kmamiz_tpu.scenarios.topology import (  # noqa: E402
    sample_topology,
    trace_group,
)

#: spans per frame come out of the sampled fanout topology; frames per
#: measured stretch keeps the whole section inside bench's budget slice
DEFAULT_FRAMES = 24


class _Worker:
    """One spawned worker subprocess + its discovered port."""

    def __init__(self, worker_id: str, wal_root: str, spawn_s: float) -> None:
        self.worker_id = worker_id
        env = dict(os.environ)
        env["KMAMIZ_WAL"] = "1"
        env["KMAMIZ_WAL_DIR"] = os.path.join(wal_root, "workers", worker_id)
        # workers are ingest-only here; keep their pollers/schedulers quiet
        env.setdefault("KMAMIZ_PROF", "0")
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "kmamiz_tpu.fleet.worker",
                "--worker-id",
                worker_id,
                "--port",
                "0",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
        )
        self.port = self._await_ready(spawn_s)

    def _await_ready(self, spawn_s: float) -> int:
        deadline = time.monotonic() + spawn_s
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                break
            if line.startswith("FLEET_WORKER_READY"):
                return int(line.split()[2])
        raise RuntimeError(f"worker {self.worker_id} never became ready")

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=30)


def _frames(tenant: str, n: int):
    """n raw ingest windows for one tenant (distinct trace ids)."""
    topo = sample_topology("fanout", random.Random(7), f"fb-{tenant}")
    out = []
    for i in range(n):
        groups = [trace_group(topo, f"fb-{tenant}", i, p) for p in range(3)]
        out.append(json.dumps(groups).encode())
    return out


def _drive(transport: HTTPTransport, worker_id: str, tenant: str, frames):
    """Ingest every frame; returns spans accepted."""
    spans = 0
    for raw in frames:
        summary = transport.ingest(worker_id, tenant, raw)
        spans += int(summary.get("spans", 0))
    return spans


def _measure_rate(transport, placements, n_frames):
    """placements: [(worker_id, tenant)]; one driver thread per tenant.
    Returns aggregate spans/sec over the slowest driver's wall."""
    frames = {t: _frames(t, n_frames) for _w, t in placements}
    # warm each tenant's shapes once so the measured stretch is steady
    for worker_id, tenant in placements:
        _drive(transport, worker_id, tenant, frames[tenant][:1])
    results = {}

    def run(worker_id: str, tenant: str) -> None:
        results[tenant] = _drive(
            transport, worker_id, tenant, frames[tenant][1:]
        )

    threads = [
        threading.Thread(target=run, args=(w, t)) for w, t in placements
    ]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    return sum(results.values()) / max(wall, 1e-9)


class _MidHandoffTransport:
    """Fires a callback between drain and WAL export (same injection the
    scenario soak uses) so the measured migration includes a frame that
    races the handoff."""

    def __init__(self, inner, on_export) -> None:
        self._inner = inner
        self._on_export = on_export

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def wal_export(self, worker_id: str, tenant: str) -> bytes:
        self._on_export()
        return self._inner.wal_export(worker_id, tenant)


def _tenant_for_each_worker(ring: HashRing):
    """A deterministic tenant name owned by every worker (search a
    numbered namespace until each worker has one)."""
    owned = {}
    i = 0
    while len(owned) < len(ring.workers) and i < 10_000:
        tenant = f"fb{i}"
        owned.setdefault(ring.owner(tenant), tenant)
        i += 1
    return [(w, owned[w]) for w in ring.workers]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--frames", type=int, default=DEFAULT_FRAMES)
    ap.add_argument(
        "--spawn-s",
        type=float,
        default=180.0,
        help="per-worker readiness deadline (jax import + server bind)",
    )
    args = ap.parse_args(argv)

    result = {
        "fleet_spans_per_sec_1": None,
        "fleet_spans_per_sec_4": None,
        "fleet_scale_efficiency": None,
        "fleet_migration_lost_spans": None,
        "fleet_migration_pass": None,
        "fleet_host_cores": os.cpu_count(),
    }
    workers = []
    with tempfile.TemporaryDirectory(prefix="fleet-bench-") as wal_root:
        try:
            ring = HashRing(["w0", "w1", "w2", "w3"])
            for w in ring.workers:
                workers.append(_Worker(w, wal_root, args.spawn_s))
            endpoints = {w.worker_id: w.endpoint for w in workers}
            transport = HTTPTransport(endpoints)
            placements = _tenant_for_each_worker(ring)

            # single-worker baseline: one tenant, its ring owner
            rate1 = _measure_rate(transport, placements[:1], args.frames)
            result["fleet_spans_per_sec_1"] = round(rate1, 0)

            # 4-worker aggregate: one tenant per worker, driven
            # concurrently (parallelism comes from the worker PROCESSES;
            # the GIL only holds these drivers' urllib waits)
            rate4 = _measure_rate(transport, placements, args.frames)
            result["fleet_spans_per_sec_4"] = round(rate4, 0)
            result["fleet_scale_efficiency"] = round(
                rate4 / max(4.0 * rate1, 1e-9), 3
            )

            # live migration with a mid-handoff frame: the tenant that
            # just soaked on worker 0 moves to worker 1
            coordinator = FleetCoordinator(ring, transport)
            src_worker, tenant = placements[0]
            target = next(w for w in ring.workers if w != src_worker)
            # pre-migration durable count on the source: the handoff
            # must land exactly this many records on the target (frames
            # lost anywhere in drain -> export -> import show up here;
            # each lost frame is >= 1 lost span)
            expected_records = transport.drain(src_worker, tenant)[
                "walRecords"
            ]
            mid = _frames(tenant, 1)
            state = {"queued": 0}

            def inject() -> None:
                if coordinator.route_ingest(tenant, mid[0]) is None:
                    state["queued"] += 1

            coordinator.swap_transport(
                _MidHandoffTransport(transport, inject)
            )
            try:
                mig = migration_mod.migrate_tenant(
                    coordinator, tenant, target
                )
            finally:
                coordinator.swap_transport(transport)
            lost_records = max(0, expected_records - mig["records"])
            lost_queued = max(0, state["queued"] - mig["queuedReleased"])
            result["fleet_migration_lost_spans"] = lost_records + lost_queued
            result["fleet_migration_pass"] = bool(
                mig["ok"]
                and result["fleet_migration_lost_spans"] == 0
                and state["queued"] == 1
            )
        except Exception as err:  # noqa: BLE001 - scorecard, not crash
            result["fleet_bench_error"] = f"{type(err).__name__}: {err}"[:300]
        finally:
            for w in workers:
                try:
                    w.stop()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
