"""Minimal WebAssembly (MVP) binary writer.

The build image ships no wasm toolchain (no tinygo/clang/wat2wasm), so the
Envoy telemetry filter binary (envoy/filter/kmamiz_filter.wasm) is
assembled directly from this pure-Python encoder — zero external
dependencies, reproducible from the tree. The subset emitted is what the
filter needs: i32 arithmetic, linear memory, globals, calls, structured
control flow, and active data segments.

Binary layout per the WebAssembly 1.0 spec (sections 1,2,3,5,6,7,10,11).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

I32 = 0x7F

# -- LEB128 -----------------------------------------------------------------


def uleb(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def sleb(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        sign = b & 0x40
        if (n == 0 and not sign) or (n == -1 and sign):
            out.append(b)
            return bytes(out)
        out.append(b | 0x80)


def _section(sid: int, payload: bytes) -> bytes:
    return bytes([sid]) + uleb(len(payload)) + payload


def _vec(items: Sequence[bytes]) -> bytes:
    return uleb(len(items)) + b"".join(items)


# -- instruction assembler ---------------------------------------------------


class Asm:
    """Appends instruction bytes; one instance per function body."""

    def __init__(self) -> None:
        self.code = bytearray()

    # control
    def block(self) -> "Asm":  # void block type
        self.code += b"\x02\x40"
        return self

    def loop(self) -> "Asm":
        self.code += b"\x03\x40"
        return self

    def if_(self, result: Optional[int] = None) -> "Asm":
        self.code += b"\x04" + (bytes([result]) if result else b"\x40")
        return self

    def else_(self) -> "Asm":
        self.code += b"\x05"
        return self

    def end(self) -> "Asm":
        self.code += b"\x0B"
        return self

    def br(self, depth: int) -> "Asm":
        self.code += b"\x0C" + uleb(depth)
        return self

    def br_if(self, depth: int) -> "Asm":
        self.code += b"\x0D" + uleb(depth)
        return self

    def return_(self) -> "Asm":
        self.code += b"\x0F"
        return self

    def call(self, func_index: int) -> "Asm":
        self.code += b"\x10" + uleb(func_index)
        return self

    def unreachable(self) -> "Asm":
        self.code += b"\x00"
        return self

    def drop(self) -> "Asm":
        self.code += b"\x1A"
        return self

    def select(self) -> "Asm":
        self.code += b"\x1B"
        return self

    # variables
    def local_get(self, i: int) -> "Asm":
        self.code += b"\x20" + uleb(i)
        return self

    def local_set(self, i: int) -> "Asm":
        self.code += b"\x21" + uleb(i)
        return self

    def local_tee(self, i: int) -> "Asm":
        self.code += b"\x22" + uleb(i)
        return self

    def global_get(self, i: int) -> "Asm":
        self.code += b"\x23" + uleb(i)
        return self

    def global_set(self, i: int) -> "Asm":
        self.code += b"\x24" + uleb(i)
        return self

    # memory (alignment hint 0 / 2 is valid for any access)
    def i32_load(self, offset: int = 0) -> "Asm":
        self.code += b"\x28\x02" + uleb(offset)
        return self

    def i32_load8_u(self, offset: int = 0) -> "Asm":
        self.code += b"\x2D\x00" + uleb(offset)
        return self

    def i32_store(self, offset: int = 0) -> "Asm":
        self.code += b"\x36\x02" + uleb(offset)
        return self

    def i32_store8(self, offset: int = 0) -> "Asm":
        self.code += b"\x3A\x00" + uleb(offset)
        return self

    # const + numeric
    def i32_const(self, v: int) -> "Asm":
        self.code += b"\x41" + sleb(v)
        return self

    def i32_eqz(self) -> "Asm":
        self.code += b"\x45"
        return self

    def i32_eq(self) -> "Asm":
        self.code += b"\x46"
        return self

    def i32_ne(self) -> "Asm":
        self.code += b"\x47"
        return self

    def i32_lt_u(self) -> "Asm":
        self.code += b"\x49"
        return self

    def i32_gt_u(self) -> "Asm":
        self.code += b"\x4B"
        return self

    def i32_le_u(self) -> "Asm":
        self.code += b"\x4D"
        return self

    def i32_ge_u(self) -> "Asm":
        self.code += b"\x4F"
        return self

    def i32_add(self) -> "Asm":
        self.code += b"\x6A"
        return self

    def i32_sub(self) -> "Asm":
        self.code += b"\x6B"
        return self

    def i32_mul(self) -> "Asm":
        self.code += b"\x6C"
        return self

    def i32_rem_u(self) -> "Asm":
        self.code += b"\x70"
        return self

    def i32_and(self) -> "Asm":
        self.code += b"\x71"
        return self

    def i32_or(self) -> "Asm":
        self.code += b"\x72"
        return self

    def i32_shl(self) -> "Asm":
        self.code += b"\x74"
        return self

    def i32_shr_u(self) -> "Asm":
        self.code += b"\x76"
        return self


# -- module builder ----------------------------------------------------------


class Module:
    def __init__(self) -> None:
        self._types: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        self._imports: List[Tuple[str, str, int]] = []  # module, name, type idx
        self._funcs: List[Tuple[int, List[int], Asm]] = []  # type, locals, body
        self._func_names: Dict[str, int] = {}
        self._exports: List[Tuple[str, int, int]] = []  # name, kind, index
        self._globals: List[Tuple[int, bool, int]] = []  # type, mut, init
        self._data: List[Tuple[int, bytes]] = []
        self._mem_pages = 1

    def type_index(self, params: Sequence[int], results: Sequence[int]) -> int:
        key = (tuple(params), tuple(results))
        for i, t in enumerate(self._types):
            if t == key:
                return i
        self._types.append(key)
        return len(self._types) - 1

    def add_import(
        self, module: str, name: str, params: Sequence[int], results: Sequence[int]
    ) -> int:
        """Returns the function index (imports come first in index space)."""
        if self._funcs:
            raise ValueError("declare all imports before functions")
        self._imports.append((module, name, self.type_index(params, results)))
        idx = len(self._imports) - 1
        self._func_names[name] = idx
        return idx

    def declare_func(
        self, name: str, params: Sequence[int], results: Sequence[int]
    ) -> int:
        """Reserve an index (so bodies can call forward references)."""
        idx = len(self._imports) + len(self._funcs)
        self._funcs.append((self.type_index(params, results), [], Asm()))
        self._func_names[name] = idx
        return idx

    def define_func(self, name: str, locals_i32: int, body: Asm) -> None:
        idx = self._func_names[name] - len(self._imports)
        type_idx = self._funcs[idx][0]
        self._funcs[idx] = (type_idx, [I32] * locals_i32, body)

    def func(self, name: str) -> int:
        return self._func_names[name]

    def add_global(self, init: int, mutable: bool = True) -> int:
        self._globals.append((I32, mutable, init))
        return len(self._globals) - 1

    def export_func(self, name: str, func_name: Optional[str] = None) -> None:
        self._exports.append((name, 0, self._func_names[func_name or name]))

    def export_memory(self, name: str = "memory") -> None:
        self._exports.append((name, 2, 0))

    def set_memory_pages(self, pages: int) -> None:
        self._mem_pages = pages

    def add_data(self, offset: int, payload: bytes) -> None:
        self._data.append((offset, payload))

    def build(self) -> bytes:
        out = bytearray(b"\x00asm\x01\x00\x00\x00")

        types = []
        for params, results in self._types:
            types.append(
                b"\x60"
                + _vec([bytes([p]) for p in params])
                + _vec([bytes([r]) for r in results])
            )
        out += _section(1, _vec(types))

        if self._imports:
            imps = []
            for module, name, tidx in self._imports:
                imps.append(
                    uleb(len(module.encode()))
                    + module.encode()
                    + uleb(len(name.encode()))
                    + name.encode()
                    + b"\x00"
                    + uleb(tidx)
                )
            out += _section(2, _vec(imps))

        out += _section(3, _vec([uleb(t) for t, _l, _b in self._funcs]))
        out += _section(5, _vec([b"\x00" + uleb(self._mem_pages)]))

        if self._globals:
            gl = []
            for vtype, mut, init in self._globals:
                gl.append(
                    bytes([vtype, 1 if mut else 0])
                    + b"\x41"
                    + sleb(init)
                    + b"\x0B"
                )
            out += _section(6, _vec(gl))

        exps = []
        for name, kind, index in self._exports:
            exps.append(
                uleb(len(name.encode()))
                + name.encode()
                + bytes([kind])
                + uleb(index)
            )
        out += _section(7, _vec(exps))

        codes = []
        for _tidx, locals_, body in self._funcs:
            decl = _vec([uleb(len(locals_)) + bytes([I32])] if locals_ else [])
            code = decl + bytes(body.code) + b"\x0B"
            codes.append(uleb(len(code)) + code)
        out += _section(10, _vec(codes))

        if self._data:
            segs = []
            for offset, payload in self._data:
                segs.append(
                    b"\x00\x41"
                    + sleb(offset)
                    + b"\x0B"
                    + uleb(len(payload))
                    + payload
                )
            out += _section(11, _vec(segs))

        return bytes(out)
