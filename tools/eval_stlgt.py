"""STLGT evaluation: prequential replay over scenario-factory labeled
windows (docs/STLGT.md#evaluation).

Replays one seeded scenario's labeled windows (scenarios/labeled.py —
ground truth comes from the composed storyline, not from heuristics over
spans) as an ONLINE forecast task: at each tick both heads train on the
windows seen so far, then forecast the NEXT window's per-endpoint
latency. Scored, TpuGraphs-style, on the tail:

- **quantile coverage**: fraction of (endpoint, tick) outcomes at or
  under the forecast p50/p95/p99 — a well-calibrated p99 covers ~99%,
  and critically keeps covering through the injected cascade ticks;
- **attribution hit-rate**: during injected-fault ticks, the fraction
  of the model's top-K blamed edges that actually touch a storyline
  fault service (vs the random-edge base rate).

The GraphSAGE baseline trains online on the same example stream with
the same update budget (the PR-2 head: point forecast + MSE — its
prediction is a conditional mean, which is exactly why its tail
coverage saturates low). Exit code 0 iff STLGT beats the baseline on
p99 coverage — the acceptance gate.

Usage: JAX_PLATFORMS=cpu python tools/eval_stlgt.py [--seed 0] [--ticks 48]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _force_cpu() -> None:
    """Drop the dev harness's tunnel-backed TPU plugin factory: it opens a
    device tunnel even under JAX_PLATFORMS=cpu and can hang the process
    (same workaround as tests/conftest.py)."""
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
    except Exception:  # noqa: BLE001 - cosmetic on stock installs
        pass


_force_cpu()

import numpy as np  # noqa: E402

#: edges blamed per fault tick (top attribution gates)
TOP_K = 5


def _pad_edges(src, dst, mask):
    from kmamiz_tpu.core.spans import _pad_size

    e = len(src)
    eb = _pad_size(e)
    src_p = np.zeros(eb, dtype=np.int32)
    dst_p = np.zeros(eb, dtype=np.int32)
    mask_p = np.zeros(eb, dtype=bool)
    src_p[:e], dst_p[:e], mask_p[:e] = src, dst, mask
    return src_p, dst_p, mask_p


def evaluate(
    seed: int = 0,
    index: int = 0,
    archetype: str = "cascade-fanout",
    ticks: int = 48,
    epochs: int = 4,
    hidden: int = 16,
    lr: float = 0.02,
    depth: int = 8,
    warmup: int = 4,
) -> dict:
    """Prequential replay -> metrics dict (see module docstring). Pure
    function of its arguments: the scenario content is compose-time
    seeded and both heads train deterministically."""
    import jax

    from kmamiz_tpu.core.spans import _pad_size
    from kmamiz_tpu.models import common, graphsage
    from kmamiz_tpu.models.stlgt import serving as stlgt_serving
    from kmamiz_tpu.models.stlgt.trainer import ContinualTrainer
    from kmamiz_tpu.scenarios import build_scenario, labeled_windows

    spec = build_scenario(archetype, seed, index, ticks)
    data = labeled_windows(spec)
    windows = data["windows"]
    names = data["names"]
    n = len(names)
    nb = _pad_size(n)
    src_p, dst_p, mask_p = _pad_edges(data["src"], data["dst"], data["mask"])
    n_edges = len(data["src"])
    svc_of = data["service_of"]
    services = data["services"]

    # STLGT: the continual trainer, driven exactly like the processor
    # fold hook drives it
    trainer = ContinualTrainer(
        depth=depth, refresh_every=1, epochs=epochs, hidden=hidden, lr=lr
    )

    # GraphSAGE baseline: same features, same online example stream,
    # same number of optimizer updates per window
    sage_params = graphsage.init_params(
        jax.random.PRNGKey(seed), hidden=hidden, num_features=10
    )
    sage_opt = graphsage.make_optimizer(lr)
    sage_opt_state = sage_opt.init(sage_params)
    sage_step = common.make_train_step(
        sage_opt, common.make_loss_fn(graphsage.forward, 1.0)
    )

    def padf(feats):
        out = np.zeros((nb, feats.shape[1]), dtype=np.float32)
        out[:n] = feats
        return out

    cov = {"stlgt_p50": [], "stlgt_p95": [], "stlgt_p99": [], "sage": []}
    attribution_hits = []
    attribution_base = []
    fault_ticks = 0
    for t, w in enumerate(windows):
        snap = {
            "features": w["features"],
            "src": data["src"],
            "dst": data["dst"],
            "mask": data["mask"],
            "names": names,
            "predicted_hour": (t + 1) % 24,
            "cache_key": (1, 0, t),
        }
        trainer.observe_fold(snap)
        if t > 0:
            prev, cur = windows[t - 1], w
            t_lat = cur["features"][:, 3]
            t_anom = (cur["features"][:, 2] > 0.10).astype(np.float32)
            nm = prev["active"] & cur["active"]
            for _ in range(epochs):
                sage_params, sage_opt_state, _loss, _aux = sage_step(
                    sage_params,
                    sage_opt_state,
                    jax.device_put(padf(prev["features"])),
                    jax.device_put(src_p),
                    jax.device_put(dst_p),
                    jax.device_put(mask_p),
                    jax.device_put(np.pad(t_lat, (0, nb - n))),
                    jax.device_put(np.pad(t_anom, (0, nb - n))),
                    jax.device_put(np.pad(nm, (0, nb - n))),
                )

        live = trainer.serving()
        if t + 1 >= len(windows) or t < warmup or live is None:
            continue
        nxt = windows[t + 1]
        act = w["active"] & nxt["active"]
        if not act.any():
            continue
        actual_ms = nxt["latency_ms"][act]

        q_ms, _prob, gate = stlgt_serving.quantile_forward(
            live["params"],
            w["features"],
            data["src"],
            data["dst"],
            data["mask"],
            live["model"],
        )
        cov["stlgt_p50"].append(np.mean(actual_ms <= q_ms[act, 0]))
        cov["stlgt_p95"].append(np.mean(actual_ms <= q_ms[act, 1]))
        cov["stlgt_p99"].append(np.mean(actual_ms <= q_ms[act, 2]))

        from kmamiz_tpu.models import serving as sage_serving

        sage_ms, _sp = sage_serving.forecast_forward(
            sage_params,
            w["features"],
            data["src"],
            data["dst"],
            data["mask"],
            graphsage,
        )
        cov["sage"].append(np.mean(actual_ms <= sage_ms[act]))

        # attribution: on injected-fault ticks, do the top-K edge gates
        # point at edges touching a storyline fault service?
        truth = set(w["truth_services"])
        if truth:
            fault_ticks += 1
            truth_idx = {services.index(s) for s in truth}

            def touches(e):
                return (
                    int(svc_of[data["src"][e]]) in truth_idx
                    or int(svc_of[data["dst"][e]]) in truth_idx
                )

            top = np.argsort(-gate)[: min(TOP_K, n_edges)]
            attribution_hits.append(
                float(np.mean([1.0 if touches(int(e)) else 0.0 for e in top]))
            )
            attribution_base.append(
                float(np.mean([1.0 if touches(e) else 0.0 for e in range(n_edges)]))
            )

    result = {
        "scenario": spec.name,
        "endpoints": n,
        "edges": n_edges,
        "ticks": ticks,
        "scored_ticks": len(cov["sage"]),
        "fault_ticks": fault_ticks,
        "stlgt_p50_coverage": round(float(np.mean(cov["stlgt_p50"])), 4),
        "stlgt_p95_coverage": round(float(np.mean(cov["stlgt_p95"])), 4),
        "stlgt_p99_coverage": round(float(np.mean(cov["stlgt_p99"])), 4),
        "sage_p99_coverage": round(float(np.mean(cov["sage"])), 4),
        "attribution_hit_rate": round(
            float(np.mean(attribution_hits)) if attribution_hits else 0.0, 4
        ),
        "attribution_base_rate": round(
            float(np.mean(attribution_base)) if attribution_base else 0.0, 4
        ),
        "trainer": trainer.status(),
    }
    result["stlgt_beats_baseline"] = bool(
        result["stlgt_p99_coverage"] > result["sage_p99_coverage"]
    )
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--archetype", default="cascade-fanout")
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args(argv)

    result = evaluate(
        seed=args.seed,
        index=args.index,
        archetype=args.archetype,
        ticks=args.ticks,
        epochs=args.epochs,
        hidden=args.hidden,
        lr=args.lr,
    )
    print("| metric | value |")
    print("|---|---|")
    for key in (
        "scenario",
        "scored_ticks",
        "fault_ticks",
        "stlgt_p50_coverage",
        "stlgt_p95_coverage",
        "stlgt_p99_coverage",
        "sage_p99_coverage",
        "attribution_hit_rate",
        "attribution_base_rate",
    ):
        print(f"| {key} | {result[key]} |")
    print(json.dumps({k: v for k, v in result.items() if k != "trainer"}))
    if result["stlgt_beats_baseline"]:
        print("PASS: STLGT p99 coverage beats the GraphSAGE baseline")
        return 0
    print("FAIL: STLGT p99 coverage does not beat the GraphSAGE baseline")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
