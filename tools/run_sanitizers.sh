#!/bin/sh
# ASan+UBSan pass over the native span loader: builds a standalone
# harness (no Python) that drives the per-call entry points, the
# persistent skip set, and the parse session through steady windows,
# replays, and 4,000 adversarial byte mutations. Any sanitizer report
# fails the run. (The round-5 pass found only memcpy/memcmp-on-nullptr
# UB for empty inputs, now guarded at the call sites.)
set -e
cd "$(dirname "$0")/.."
g++ -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
    -pthread -std=c++17 -o /tmp/kmamiz_asan_parse \
    tools/asan_harness.cpp native/kmamiz_spans.cpp \
    native/kmamiz_json.cpp native/kmamiz_native.cpp
ASAN_OPTIONS=halt_on_error=1 UBSAN_OPTIONS=halt_on_error=1 \
    /tmp/kmamiz_asan_parse
