// ASan/UBSan harness for the native span loader: exercises the per-call
// paths, the persistent skip set, and the parse session across repeated
// windows and adversarial mutations.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>
extern "C" {
unsigned char* km_parse_spans_mt(const char*, size_t, const char*, size_t, int, size_t*);
unsigned char* km_parse_spans_hs(void*, const char*, size_t, int, size_t*);
unsigned char* km_parse_spans_sess(void*, void*, const char*, size_t, int, size_t*);
void* km_skipset_new(); void km_skipset_free(void*);
long long km_skipset_extend(void*, const char*, size_t);
void km_skipset_clear(void*);
void* km_session_new(); void km_session_free(void*);
void km_session_ack(void*, unsigned, unsigned);
unsigned char* km_split_groups(const char*, size_t, int, size_t*);
void km_free(unsigned char*);
}

static std::string make_window(int base, int traces) {
  std::string s = "[";
  for (int t = 0; t < traces; ++t) {
    if (t) s += ",";
    char buf[1024];
    snprintf(buf, sizeof buf,
      "[{\"traceId\":\"t%d\",\"id\":\"p%d\",\"kind\":\"SERVER\","
      "\"name\":\"svc%d.ns.svc.cluster.local:80/*\","
      "\"timestamp\":%d,\"duration\":55,"
      "\"tags\":{\"http.method\":\"GET\",\"http.status_code\":\"200\","
      "\"http.url\":\"http://svc%d.ns/api\",\"istio.canonical_service\":\"svc%d\","
      "\"istio.namespace\":\"ns\",\"istio.canonical_revision\":\"v1\"}},"
      "{\"traceId\":\"t%d\",\"id\":\"c%d\",\"parentId\":\"p%d\",\"kind\":\"CLIENT\","
      "\"name\":\"d%d.ns.svc.cluster.local:80/*\",\"timestamp\":%d,\"duration\":31}]",
      base + t, base + t, (base + t) % 37, (base + t) % 37, (base + t) % 37,
      base + t, base + t, base + t, (base + t) % 11, base + t + 1);
    s += buf;
  }
  s += "]";
  return s;
}

int main() {
  unsigned int no_skip = 0;
  const char* empty = reinterpret_cast<const char*>(&no_skip);
  void* ss = km_skipset_new();
  void* sess = km_session_new();

  // steady windows through the session+skipset, with incremental extends
  for (int w = 0; w < 12; ++w) {
    std::string win = make_window(w * 50, 50);
    size_t out_len = 0;
    unsigned char* out = km_parse_spans_sess(sess, ss, win.data(), win.size(), 1, &out_len);
    if (!out) { printf("unexpected reject w=%d\n", w); return 2; }
    // ack roughly (large counts clamp internally)
    km_session_ack(sess, 1u << 20, 1u << 20);
    km_free(out);
    // register this window's ids into the skip set
    std::string entries;
    for (int t = 0; t < 50; ++t) {
      char idb[32]; int n = snprintf(idb, sizeof idb, "t%d", w * 50 + t);
      unsigned char hdr[5]; hdr[0] = 1; unsigned len = (unsigned)n;
      memcpy(hdr + 1, &len, 4);
      entries.append(reinterpret_cast<char*>(hdr), 5);
      entries.append(idb, n);
    }
    if (km_skipset_extend(ss, entries.data(), entries.size()) < 0) return 3;
    // replay: everything must dedup
    out = km_parse_spans_hs(ss, win.data(), win.size(), 1, &out_len);
    if (!out) return 4;
    km_free(out);
  }
  km_skipset_clear(ss);

  // empty-id edge cases: a span with no "id" is claimed with an empty
  // key; a sibling probing parentId:"" must hit the empty-key compare
  // in BOTH SpanIdTable::claim and ::find without UB
  {
    const char* edge =
        "[[{\"traceId\":\"e1\",\"kind\":\"SERVER\",\"name\":\"n\","
        "\"timestamp\":1,\"duration\":5},"
        "{\"traceId\":\"e1\",\"id\":\"b\",\"parentId\":\"\","
        "\"kind\":\"SERVER\",\"name\":\"n\",\"timestamp\":2,"
        "\"duration\":5},"
        "{\"traceId\":\"e1\",\"id\":\"\",\"parentId\":\"b\","
        "\"kind\":\"CLIENT\",\"name\":\"n\",\"timestamp\":3,"
        "\"duration\":5}]]";
    size_t out_len = 0;
    for (int threads : {1, 4}) {
      unsigned char* out = km_parse_spans_mt(empty, 4, edge, strlen(edge),
                                             threads, &out_len);
      if (out) km_free(out);
    }
  }

  // fuzz: mutations through every entry point (incl. MT threads)
  std::mt19937 rng(99);
  std::string base = make_window(10000, 6);
  for (int i = 0; i < 4000; ++i) {
    std::string buf;
    switch (rng() % 4) {
      case 0: { buf.resize(rng() % 200); for (auto& c : buf) c = (char)(rng() & 0xff); break; }
      case 1: buf = base.substr(0, rng() % (base.size() + 1)); break;
      case 2: { buf = base; for (int k = rng() % 6 + 1; k--;) buf[rng() % buf.size()] = (char)(rng() & 0xff); break; }
      default: { buf = base; const char ins[] = "[]{}\",\\\x00\x01"; for (int k = rng() % 8 + 1; k--;) buf.insert(buf.begin() + rng() % (buf.size() + 1), ins[rng() % 8]); break; }
    }
    size_t out_len = 0;
    unsigned char* out;
    switch (i % 4) {
      case 0: out = km_parse_spans_mt(empty, 4, buf.data(), buf.size(), 1, &out_len); break;
      case 1: out = km_parse_spans_mt(empty, 4, buf.data(), buf.size(), 4, &out_len); break;
      case 2: out = km_parse_spans_hs(ss, buf.data(), buf.size(), 1, &out_len); break;
      default: out = km_parse_spans_sess(sess, ss, buf.data(), buf.size(), 2, &out_len); break;
    }
    if (out) km_free(out);
    // malformed skipset extends
    if (i % 16 == 0 && !buf.empty())
      km_skipset_extend(ss, buf.data(), buf.size() % 64);
    // split_groups fuzz
    if (i % 8 == 0) {
      unsigned char* sp = km_split_groups(buf.data(), buf.size(), 3, &out_len);
      if (sp) km_free(sp);
    }
  }
  km_session_free(sess);
  km_skipset_free(ss);
  printf("ASAN harness done\n");
  return 0;
}
