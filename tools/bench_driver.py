"""Bench artifact recorder: run bench.py, wrap its result, fail loudly.

Writes the ``BENCH_r<N>.json`` wrapper shape every round has used —
``{"n": N, "cmd": [...], "rc": int, "tail": str, "parsed": dict}`` —
but REFUSES to record an unparsable round: BENCH_r04/r05 were silently
written with ``"parsed": null`` (the bench crashed past its JSON line;
the wrapper shrugged), and the SLO gate then skipped them for two PRs.
Now a round with no parseable result line exits nonzero with the reason
on stderr and writes NOTHING, so the broken run is fixed instead of
archived; ``tools/slo_report.py --check`` enforces the same contract on
the reading side.

    python tools/bench_driver.py                 # next round number, repo root
    python tools/bench_driver.py --n 6           # explicit round
    python tools/bench_driver.py -- --quick      # args after -- go to bench.py
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: keep enough of stdout+stderr for slo_report's last-JSON-line fallback
#: and for a human reading a failed round's traceback
_TAIL_BYTES = 65536


def parse_result(tail: str):
    """The LAST parseable JSON object line in the output, or None."""
    for line in reversed(tail.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict):
                return doc
    return None


def next_round(root: str) -> int:
    best = 0
    for name in os.listdir(root):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=None, help="round number")
    ap.add_argument("--root", default=_ROOT, help="artifact directory")
    ap.add_argument(
        "--timeout", type=float, default=3600.0, help="bench wall cap (s)"
    )
    ap.add_argument(
        "bench_args", nargs="*", help="extra args passed through to bench.py"
    )
    args = ap.parse_args(argv)

    n = args.n if args.n is not None else next_round(args.root)
    cmd = [sys.executable, os.path.join(_ROOT, "bench.py"), *args.bench_args]
    try:
        proc = subprocess.run(
            cmd,
            cwd=_ROOT,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=args.timeout,
        )
        out, rc = proc.stdout or "", proc.returncode
    except subprocess.TimeoutExpired as err:
        captured = err.stdout or b""
        if isinstance(captured, bytes):
            captured = captured.decode("utf-8", "replace")
        print(
            f"bench round r{n:02d} timed out after {args.timeout:.0f}s; "
            "no artifact written",
            file=sys.stderr,
        )
        sys.stderr.write(captured[-2000:])
        return 3

    tail = out[-_TAIL_BYTES:]
    parsed = parse_result(tail)
    if parsed is None:
        # the failure mode that produced the null-parsed r04/r05
        # artifacts: refuse to archive it
        print(
            f"bench round r{n:02d} produced no parseable JSON result line "
            f"(rc={rc}); no artifact written — last output follows",
            file=sys.stderr,
        )
        sys.stderr.write(tail[-2000:] + "\n")
        return 3
    if rc != 0:
        print(
            f"bench round r{n:02d} exited rc={rc}; no artifact written",
            file=sys.stderr,
        )
        sys.stderr.write(tail[-2000:] + "\n")
        return rc

    wrapper = {"n": n, "cmd": cmd, "rc": rc, "tail": tail, "parsed": parsed}
    path = os.path.join(args.root, f"BENCH_r{n:02d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(wrapper, f)
    os.replace(tmp, path)
    print(f"recorded {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
