"""Subset WebAssembly interpreter: executes the in-tree filter binary.

No wasm runtime ships in the image, so the tests run the ACTUAL
envoy/filter/kmamiz_filter.wasm artifact through this interpreter against
mocked proxy-wasm host functions and compare the logged lines with the
Python spec twin (kmamiz_tpu.core.envoy_filter). Covers the MVP subset
tools/wasm_asm.py emits — i32 ops, linear memory, globals, structured
control flow, calls — and raises on anything outside it.
"""
from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional, Tuple

PAGE = 65536


def _read_uleb(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _read_sleb(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            if b & 0x40:
                result |= -(1 << shift)
            return result, pos


def _u32(v: int) -> int:
    return v & 0xFFFFFFFF


def _s32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


class WasmError(RuntimeError):
    pass


class Function:
    def __init__(self, type_idx: int, locals_n: int, body: bytes) -> None:
        self.type_idx = type_idx
        self.locals_n = locals_n
        self.body = body
        self.jumps: Dict[int, Tuple[int, Optional[int]]] = {}
        self._scan()

    def _scan(self) -> None:
        """Precompute (end_pc, else_pc) for every block/loop/if start."""
        stack: List[int] = []
        elses: Dict[int, int] = {}
        pos = 0
        buf = self.body
        while pos < len(buf):
            op = buf[pos]
            start = pos
            pos += 1
            if op in (0x02, 0x03, 0x04):  # block/loop/if
                pos += 1  # blocktype byte
                stack.append(start)
            elif op == 0x05:  # else
                elses[stack[-1]] = pos
            elif op == 0x0B:  # end
                if stack:
                    opener = stack.pop()
                    self.jumps[opener] = (pos, elses.get(opener))
            elif op in (0x0C, 0x0D, 0x10, 0x20, 0x21, 0x22, 0x23, 0x24):
                _, pos = _read_uleb(buf, pos)
            elif op in (0x28, 0x2D, 0x36, 0x3A):
                _, pos = _read_uleb(buf, pos)
                _, pos = _read_uleb(buf, pos)
            elif op == 0x41:
                _, pos = _read_sleb(buf, pos)
            # all other supported opcodes have no immediates


class Module:
    def __init__(self, binary: bytes) -> None:
        if binary[:8] != b"\x00asm\x01\x00\x00\x00":
            raise WasmError("bad magic/version")
        self.types: List[Tuple[List[int], List[int]]] = []
        self.imports: List[Tuple[str, str, int]] = []
        self.functions: List[Function] = []
        self.globals_init: List[int] = []
        self.exports: Dict[str, Tuple[int, int]] = {}  # name -> (kind, idx)
        self.mem_pages = 1
        self.data: List[Tuple[int, bytes]] = []
        self._parse(binary)

    def _parse(self, binary: bytes) -> None:
        pos = 8
        func_types: List[int] = []
        while pos < len(binary):
            sid = binary[pos]
            pos += 1
            size, pos = _read_uleb(binary, pos)
            body = binary[pos : pos + size]
            pos += size
            if sid == 1:
                self._parse_types(body)
            elif sid == 2:
                self._parse_imports(body)
            elif sid == 3:
                n, p = _read_uleb(body, 0)
                for _ in range(n):
                    t, p = _read_uleb(body, p)
                    func_types.append(t)
            elif sid == 5:
                n, p = _read_uleb(body, 0)
                flags, p = _read_uleb(body, p)
                self.mem_pages, p = _read_uleb(body, p)
            elif sid == 6:
                self._parse_globals(body)
            elif sid == 7:
                self._parse_exports(body)
            elif sid == 10:
                self._parse_code(body, func_types)
            elif sid == 11:
                self._parse_data(body)
            # other sections ignored

    def _parse_types(self, body: bytes) -> None:
        n, p = _read_uleb(body, 0)
        for _ in range(n):
            if body[p] != 0x60:
                raise WasmError("expected functype")
            p += 1
            np_, p = _read_uleb(body, p)
            params = list(body[p : p + np_])
            p += np_
            nr, p = _read_uleb(body, p)
            results = list(body[p : p + nr])
            p += nr
            self.types.append((params, results))

    def _parse_imports(self, body: bytes) -> None:
        n, p = _read_uleb(body, 0)
        for _ in range(n):
            ml, p = _read_uleb(body, p)
            mod = body[p : p + ml].decode()
            p += ml
            nl, p = _read_uleb(body, p)
            name = body[p : p + nl].decode()
            p += nl
            kind = body[p]
            p += 1
            if kind != 0:
                raise WasmError("only function imports supported")
            tidx, p = _read_uleb(body, p)
            self.imports.append((mod, name, tidx))

    def _parse_globals(self, body: bytes) -> None:
        n, p = _read_uleb(body, 0)
        for _ in range(n):
            p += 2  # valtype + mutability
            if body[p] != 0x41:
                raise WasmError("only i32.const global initializers")
            v, p = _read_sleb(body, p + 1)
            if body[p] != 0x0B:
                raise WasmError("bad global init")
            p += 1
            self.globals_init.append(v)

    def _parse_exports(self, body: bytes) -> None:
        n, p = _read_uleb(body, 0)
        for _ in range(n):
            nl, p = _read_uleb(body, p)
            name = body[p : p + nl].decode()
            p += nl
            kind = body[p]
            p += 1
            idx, p = _read_uleb(body, p)
            self.exports[name] = (kind, idx)

    def _parse_code(self, body: bytes, func_types: List[int]) -> None:
        n, p = _read_uleb(body, 0)
        for i in range(n):
            size, p = _read_uleb(body, p)
            code = body[p : p + size]
            p += size
            q = 0
            ndecl, q = _read_uleb(code, q)
            locals_n = 0
            for _ in range(ndecl):
                cnt, q = _read_uleb(code, q)
                q += 1  # valtype
                locals_n += cnt
            self.functions.append(Function(func_types[i], locals_n, code[q:]))

    def _parse_data(self, body: bytes) -> None:
        n, p = _read_uleb(body, 0)
        for _ in range(n):
            mode, p = _read_uleb(body, p)
            if mode != 0 or body[p] != 0x41:
                raise WasmError("only active i32.const data segments")
            offset, p = _read_sleb(body, p + 1)
            if body[p] != 0x0B:
                raise WasmError("bad data offset expr")
            p += 1
            ln, p = _read_uleb(body, p)
            self.data.append((offset, body[p : p + ln]))
            p += ln


HostFn = Callable[..., int]


class Instance:
    """module + host functions keyed 'module.name'; host fns receive
    (instance, *args)."""

    def __init__(self, module: Module, host: Dict[str, HostFn]) -> None:
        self.module = module
        self.host = host
        self.memory = bytearray(module.mem_pages * PAGE)
        self.globals = list(module.globals_init)
        for offset, payload in module.data:
            self.memory[offset : offset + len(payload)] = payload
        self.n_imports = len(module.imports)

    # -- memory helpers for host functions ----------------------------------
    # bounds-checked like a real wasm host: silent bytearray growth would
    # hide module bugs (e.g. allocations past the arena) from the tests

    def _check(self, ptr: int, size: int) -> None:
        if ptr < 0 or size < 0 or ptr + size > len(self.memory):
            raise WasmError(
                f"out-of-bounds memory access: [{ptr}, {ptr + size}) "
                f"of {len(self.memory)}"
            )

    def read(self, ptr: int, size: int) -> bytes:
        self._check(ptr, size)
        return bytes(self.memory[ptr : ptr + size])

    def write(self, ptr: int, data: bytes) -> None:
        self._check(ptr, len(data))
        self.memory[ptr : ptr + len(data)] = data

    def write_u32(self, ptr: int, v: int) -> None:
        struct.pack_into("<I", self.memory, ptr, _u32(v))

    def read_u32(self, ptr: int) -> int:
        return struct.unpack_from("<I", self.memory, ptr)[0]

    def invoke(self, name: str, *args: int) -> List[int]:
        kind, idx = self.module.exports[name]
        if kind != 0:
            raise WasmError(f"{name} is not a function export")
        return self._call(idx, list(args))

    def _call(self, func_idx: int, args: List[int]) -> List[int]:
        if func_idx < self.n_imports:
            mod, name, tidx = self.module.imports[func_idx]
            fn = self.host.get(f"{mod}.{name}")
            if fn is None:
                raise WasmError(f"missing host function {mod}.{name}")
            result = fn(self, *args)
            _params, results = self.module.types[tidx]
            return [] if not results else [_u32(int(result or 0))]
        f = self.module.functions[func_idx - self.n_imports]
        locals_ = list(args) + [0] * f.locals_n
        return self._exec(f, locals_)

    def _exec(self, f: Function, locals_: List[int]) -> List[int]:
        buf = f.body
        stack: List[int] = []
        # control stack entries: (kind, start_pc, end_pc, else_pc)
        ctrl: List[Tuple[int, int, int, Optional[int]]] = []
        pos = 0
        _params, results = self.module.types[f.type_idx]

        def branch(depth: int) -> int:
            nonlocal ctrl
            target = len(ctrl) - 1 - depth
            kind, start, end, _els = ctrl[target]
            del ctrl[target + 1 :]
            if kind == 0x03:  # loop: jump back to the loop body start
                return start
            ctrl.pop()
            return end

        while True:
            if pos >= len(buf):
                break
            op = buf[pos]
            ipos = pos
            pos += 1
            if op == 0x02 or op == 0x03:  # block / loop
                end, _els = f.jumps[ipos]
                pos += 1
                ctrl.append((op, pos, end, None))
            elif op == 0x04:  # if
                end, els = f.jumps[ipos]
                pos += 1
                cond = stack.pop()
                ctrl.append((op, pos, end, els))
                if not cond:
                    pos = els if els is not None else end
                    if els is None:
                        ctrl.pop()
            elif op == 0x05:  # else: taken branch falls here -> skip to end
                kind, start, end, _els = ctrl.pop()
                pos = end
            elif op == 0x0B:  # end
                if ctrl:
                    ctrl.pop()
                else:
                    break
            elif op == 0x0C:  # br
                depth, pos = _read_uleb(buf, pos)
                pos = branch(depth)
            elif op == 0x0D:  # br_if
                depth, pos = _read_uleb(buf, pos)
                if stack.pop():
                    pos = branch(depth)
            elif op == 0x0F:  # return
                break
            elif op == 0x10:  # call
                fidx, pos = _read_uleb(buf, pos)
                if fidx < self.n_imports:
                    nparams = len(self.module.types[self.module.imports[fidx][2]][0])
                else:
                    nparams = len(
                        self.module.types[
                            self.module.functions[fidx - self.n_imports].type_idx
                        ][0]
                    )
                callee_args = stack[len(stack) - nparams :]
                del stack[len(stack) - nparams :]
                stack.extend(self._call(fidx, callee_args))
            elif op == 0x1A:  # drop
                stack.pop()
            elif op == 0x1B:  # select
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == 0x20:
                i, pos = _read_uleb(buf, pos)
                stack.append(locals_[i])
            elif op == 0x21:
                i, pos = _read_uleb(buf, pos)
                locals_[i] = stack.pop()
            elif op == 0x22:
                i, pos = _read_uleb(buf, pos)
                locals_[i] = stack[-1]
            elif op == 0x23:
                i, pos = _read_uleb(buf, pos)
                stack.append(self.globals[i])
            elif op == 0x24:
                i, pos = _read_uleb(buf, pos)
                self.globals[i] = stack.pop()
            elif op == 0x28:  # i32.load
                _a, pos = _read_uleb(buf, pos)
                off, pos = _read_uleb(buf, pos)
                addr = _u32(stack.pop()) + off
                stack.append(struct.unpack_from("<I", self.memory, addr)[0])
            elif op == 0x2D:  # i32.load8_u
                _a, pos = _read_uleb(buf, pos)
                off, pos = _read_uleb(buf, pos)
                addr = _u32(stack.pop()) + off
                stack.append(self.memory[addr])
            elif op == 0x36:  # i32.store
                _a, pos = _read_uleb(buf, pos)
                off, pos = _read_uleb(buf, pos)
                v = stack.pop()
                addr = _u32(stack.pop()) + off
                struct.pack_into("<I", self.memory, addr, _u32(v))
            elif op == 0x3A:  # i32.store8
                _a, pos = _read_uleb(buf, pos)
                off, pos = _read_uleb(buf, pos)
                v = stack.pop()
                addr = _u32(stack.pop()) + off
                self.memory[addr] = v & 0xFF
            elif op == 0x41:
                v, pos = _read_sleb(buf, pos)
                stack.append(_u32(v))
            elif op == 0x45:
                stack.append(1 if stack.pop() == 0 else 0)
            elif op in (0x46, 0x47, 0x49, 0x4B, 0x4D, 0x4F):
                b = _u32(stack.pop())
                a = _u32(stack.pop())
                stack.append(
                    {
                        0x46: a == b,
                        0x47: a != b,
                        0x49: a < b,
                        0x4B: a > b,
                        0x4D: a <= b,
                        0x4F: a >= b,
                    }[op]
                    and 1
                    or 0
                )
            elif op in (0x6A, 0x6B, 0x6C, 0x70, 0x71, 0x72, 0x74, 0x76):
                b = stack.pop()
                a = stack.pop()
                if op == 0x6A:
                    r = a + b
                elif op == 0x6B:
                    r = a - b
                elif op == 0x6C:
                    r = a * b
                elif op == 0x70:
                    r = _u32(a) % _u32(b) if b else 0
                elif op == 0x71:
                    r = a & b
                elif op == 0x72:
                    r = a | b
                elif op == 0x74:
                    r = a << (b & 31)
                else:  # 0x76 shr_u
                    r = _u32(a) >> (b & 31)
                stack.append(_u32(r))
            else:
                raise WasmError(f"unsupported opcode 0x{op:02x} at {ipos}")

        return [_u32(v) for v in stack[len(stack) - len(results) :]] if results else []
