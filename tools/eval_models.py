"""Reproducible evaluation of the GraphSAGE and GAT heads (VERDICT r1 #6).

Synthesizes a mesh with time-windowed faults via the MicroViSim-equivalent
simulator, trains each head on the first 75% of hourly slots, and reports
held-out anomaly precision/recall/F1 and latency MAE against the
persistence baseline (next slot = current slot). Prints a markdown table;
the committed numbers live in MODELS.md.

Usage: JAX_PLATFORMS=cpu python tools/eval_models.py [--epochs N] [--seed S]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _force_cpu() -> None:
    """Drop the dev harness's tunnel-backed TPU plugin factory: it opens a
    device tunnel even under JAX_PLATFORMS=cpu and can hang the process
    (same workaround as tests/conftest.py)."""
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
    except Exception:  # noqa: BLE001 - cosmetic on stock installs
        pass


_force_cpu()

import numpy as np

EVAL_YAML = """
servicesInfo:
  - namespace: mesh
    services:
      - serviceName: gateway
        versions:
          - version: v1
            replica: 2
            endpoints:
              - endpointId: gw-get
                endpointInfo: { path: /api/entry, method: get }
      - serviceName: catalog
        versions:
          - version: v1
            replica: 2
            endpoints:
              - endpointId: catalog-list
                endpointInfo: { path: /api/catalog, method: get }
              - endpointId: catalog-item
                endpointInfo: { path: /api/catalog/item, method: get }
      - serviceName: pricing
        versions:
          - version: v1
            replica: 1
            endpoints:
              - endpointId: price-get
                endpointInfo: { path: /api/price, method: get }
      - serviceName: inventory
        versions:
          - version: v1
            replica: 1
            endpoints:
              - endpointId: inv-get
                endpointInfo: { path: /api/inventory, method: get }
      - serviceName: db
        versions:
          - version: v1
            replica: 1
            endpoints:
              - endpointId: db-query
                endpointInfo: { path: /query, method: post }
endpointDependencies:
  - endpointId: gw-get
    isExternal: true
    dependOn:
      - endpointId: catalog-list
      - endpointId: catalog-item
  - endpointId: catalog-list
    dependOn:
      - endpointId: price-get
      - endpointId: db-query
  - endpointId: catalog-item
    dependOn:
      - endpointId: price-get
      - endpointId: inv-get
  - endpointId: inv-get
    dependOn:
      - endpointId: db-query
loadSimulation:
  config:
    simulationDurationInDays: 4
    overloadErrorRateIncreaseFactor: 3
  serviceMetrics: []
  endpointMetrics:
    - endpointId: gw-get
      delay: { latencyMs: 25, jitterMs: 6 }
      errorRatePercent: 1
      expectedExternalDailyRequestCount: 9600
    - endpointId: catalog-list
      delay: { latencyMs: 15, jitterMs: 4 }
      errorRatePercent: 1
    - endpointId: catalog-item
      delay: { latencyMs: 12, jitterMs: 4 }
      errorRatePercent: 1
    - endpointId: price-get
      delay: { latencyMs: 8, jitterMs: 2 }
      errorRatePercent: 1
    - endpointId: inv-get
      delay: { latencyMs: 9, jitterMs: 2 }
      errorRatePercent: 1
    - endpointId: db-query
      delay: { latencyMs: 5, jitterMs: 1 }
      errorRatePercent: 1
  faultInjection:
    - type: increase-error-rate
      targets:
        services: []
        endpoints:
          - endpointId: db-query
      timePeriods:
        # a RECURRING nightly window (same hours every day): train days
        # teach the periodicity, the held-out day grades forecasting the
        # window start the persistence baseline cannot see coming
        - startTime: { day: 1, hour: 5 }
          durationHours: 4
          probabilityPercent: 100
        - startTime: { day: 2, hour: 5 }
          durationHours: 4
          probabilityPercent: 100
        - startTime: { day: 3, hour: 5 }
          durationHours: 4
          probabilityPercent: 100
        - startTime: { day: 4, hour: 5 }
          durationHours: 4
          probabilityPercent: 100
      increaseErrorRatePercent: 70
    - type: increase-error-rate
      targets:
        services: []
        endpoints:
          - endpointId: price-get
      timePeriods:
        - startTime: { day: 2, hour: 14 }
          durationHours: 3
          probabilityPercent: 100
        - startTime: { day: 4, hour: 1 }
          durationHours: 3
          probabilityPercent: 100
      increaseErrorRatePercent: 60
    - type: increase-latency
      targets:
        services: []
        endpoints:
          - endpointId: inv-get
      timePeriods:
        - startTime: { day: 3, hour: 9 }
          durationHours: 4
          probabilityPercent: 100
      increaseLatencyMs: 220
"""


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=60)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--hidden", type=int, default=32)
    args = parser.parse_args()

    from kmamiz_tpu.models import gat, graphsage, trainer
    from kmamiz_tpu.simulator.simulator import Simulator

    result = Simulator().generate_simulation_data(
        EVAL_YAML, 0.0, rng=np.random.default_rng(args.seed)
    )
    assert result.validation_error_message == ""
    assert result.converting_error_message == ""

    rows = []
    shared_dataset = None
    for name, model in (("GraphSAGE", graphsage), ("GAT", gat)):
        _res, metrics, dataset = trainer.train_on_simulation(
            result.endpoint_dependencies,
            result.realtime_data_per_slot,
            result.replica_counts,
            epochs=args.epochs,
            hidden=args.hidden,
            seed=args.seed,
            model=model,
        )
        shared_dataset = dataset
        rows.append((name, metrics))

    # baselines score the SAME held-out slots (shared split definition)
    _train_set, eval_set = trainer.temporal_split(shared_dataset, 0.75)
    base_rate = rows[0][1].anomaly_base_rate
    rows.append(("persistence skyline", trainer.evaluate_baseline(eval_set)))
    rows.append(
        (
            "naive: random @ base rate",
            trainer.evaluate_naive(eval_set, rate=base_rate, seed=args.seed),
        )
    )
    rows.append(
        ("naive: flag everything", trainer.evaluate_naive(eval_set, rate=1.0))
    )

    print(
        f"\nheld-out slots: {len(eval_set.features)} "
        f"(of {len(shared_dataset.features)}), "
        f"anomaly base rate {rows[0][1].anomaly_base_rate:.3f}, "
        f"epochs {args.epochs}, seed {args.seed}\n"
    )
    print("| model | precision | recall | F1 | latency MAE (ms) |")
    print("|---|---|---|---|---|")
    for name, m in rows:
        print(
            f"| {name} | {m.anomaly_precision:.3f} | {m.anomaly_recall:.3f} "
            f"| {m.anomaly_f1:.3f} | {m.latency_mae_ms:.2f} |"
        )


if __name__ == "__main__":
    main()
