"""Dev-box probe for the bench headline: the steady-state deployed
streaming ingest (bench.py's HEADLINE section), with the full per-chunk
phase breakdown printed per rep — for finding where the critical path
goes without running the whole bench.

Usage: python tools/probe_headline.py [reps] [chunks]
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, "/root/repo")

from bench import critical_path_ms, make_raw_window  # noqa: E402


def main() -> None:
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    from kmamiz_tpu.server.processor import (
        DEFAULT_STREAM_CHUNKS,
        DataProcessor,
    )

    n_chunks = int(sys.argv[2]) if len(sys.argv) > 2 else DEFAULT_STREAM_CHUNKS
    e2e_traces = 150_000
    chunk_traces = e2e_traces // n_chunks
    n_services, urls_per_svc = 1_000, 10

    def make_chunks(prefix: str):
        return [
            make_raw_window(
                chunk_traces,
                7,
                t_start=i * chunk_traces,
                trace_prefix=prefix,
                n_services=n_services,
                urls_per_service=urls_per_svc,
            )
            for i in range(n_chunks)
        ]

    bench_clock = {"ms": 1_700_000_000_000.0}
    dp = DataProcessor(
        trace_source=lambda lb, t, lim: [],
        now_ms=lambda: bench_clock["ms"],
    )
    t0 = time.perf_counter()
    cold = dp.ingest_raw_stream(iter(make_chunks("c")))
    print(
        f"cold: wall {(time.perf_counter() - t0) * 1000:.0f} ms  cp "
        f"{critical_path_ms(cold['chunk_detail'], cold['drain_ms']):.0f} ms"
    )
    bench_clock["ms"] += 301_000
    t0 = time.perf_counter()
    warm = dp.ingest_raw_stream(iter(make_chunks("s")))
    print(
        f"steady-warmup: wall {(time.perf_counter() - t0) * 1000:.0f} ms  cp "
        f"{critical_path_ms(warm['chunk_detail'], warm['drain_ms']):.0f} ms"
    )
    n_spans = e2e_traces * 7
    for k in range(reps):
        bench_clock["ms"] += 301_000
        chunks = make_chunks(f"r{k}x")
        t0 = time.perf_counter()
        s = dp.ingest_raw_stream(iter(chunks))
        wall_ms = (time.perf_counter() - t0) * 1000
        cp = critical_path_ms(s["chunk_detail"], s["drain_ms"])
        print(
            f"rep {k}: wall {wall_ms:.0f} ms  cp {cp:.0f} ms  "
            f"-> {n_spans / cp * 1000 / 1e6:.2f}M spans/s  "
            f"drain {s['drain_ms']:.0f} ms"
        )
        for d in s["chunk_detail"]:
            print(
                f"    spans {d['spans']:7d}  parse {d['parse_ms']:7.1f}  "
                f"merge {d['merge_ms']:7.1f}  transfer {d['transfer_ms']:7.1f}"
            )


if __name__ == "__main__":
    main()
