"""graftprof CLI: render, capture, and diff hot-path attribution profiles.

Three modes over the same artifact formats (a "kmamiz-graftprof"
profile, or a "kmamiz-flight" recorder dump — both render identically):

    # per-phase report of an artifact (scenario flight box, bench
    # profile, /debug/graftprof download)
    python tools/graftprof.py report kmamiz-data/flight/flight-....json
    python tools/graftprof.py kmamiz-data/flight/flight-....json --json

    # regression gate: candidate vs baseline per-phase p95, exit 1 on
    # any phase past its threshold (tools/slo_report.py --check uses the
    # same thresholds for the prof_* bench keys). When the candidate is
    # a failed scenario cell's flight box, the output also carries a
    # "blame" block — the gate/phase attribution the graftsoak sweep
    # records per cell (bisect a failure against the sweep's last
    # passing flight for the same archetype; docs/OBSERVABILITY.md)
    python tools/graftprof.py --diff baseline.json candidate.json

    # seeded capture: drive a synthetic collect-tick + raw-ingest
    # workload (the bench's seed-0 shape, KMAMIZ_PARSE_THREADS=2 so the
    # native merge barrier skew is visible) and write a profile artifact
    python tools/graftprof.py --capture profile.json --ticks 4

The capture is the zero-infrastructure demo of the acceptance bar:
>=90% of dp_tick wall attributed to named phases, per-shard native
merge lock-wait nonzero at two parse threads.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, "/root/repo")


def _load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _flight_blame(cand_doc: dict, regressions) -> dict:
    """Auto-triage bisection for a scenario flight candidate: the
    runner stamps the failed gates into the flight's ``detail``; map
    the first (sorted, deterministic) onto its owning phase and attach
    the diff's regressed phases as supporting evidence. Empty dict for
    non-scenario candidates."""
    if cand_doc.get("kind") != "kmamiz-flight":
        return {}
    trigger = str(cand_doc.get("trigger", ""))
    if not trigger.startswith("scenario-"):
        return {}
    from kmamiz_tpu.soak.triage import GATE_PHASE

    detail = str(cand_doc.get("detail", ""))
    if detail.startswith("crashed"):
        gates = ["crashed"]
    else:
        gates = sorted(g for g in detail.split(",") if g)
    gate = gates[0] if gates else "unknown"
    return {
        "scenario": trigger[len("scenario-"):],
        "blamed_gate": gate,
        "blamed_phase": GATE_PHASE.get(gate, "unknown"),
        "failed_gates": gates,
        "regressed_phases": [r["phase"] for r in regressions[:4]],
    }


def _capture(out_path: str, ticks: int, threads: int, seed: int) -> dict:
    """Run the seeded workload in-process and write a profile artifact."""
    os.environ["KMAMIZ_PROF"] = "1"
    os.environ.setdefault("KMAMIZ_PARSE_THREADS", str(threads))
    import kmamiz_tpu.telemetry as telemetry
    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.synth import make_raw_window
    from kmamiz_tpu.telemetry.profiling import report
    from kmamiz_tpu.telemetry.tracing import TRACER

    telemetry.reset_for_tests()
    rng_base = 1_700_000_000_000_000 + seed

    def tick_traces(tick_id: int):
        groups = []
        for t in range(64):
            g = []
            for j in range(7):
                svc = (seed + j) % 5
                g.append(
                    {
                        "traceId": f"{tick_id}-t{t}",
                        "id": f"{tick_id}-{t}-{j}",
                        "parentId": f"{tick_id}-{t}-{j - 1}" if j else None,
                        "kind": "SERVER" if j % 2 == 0 else "CLIENT",
                        "name": f"svc{svc}.ns.svc.cluster.local:80/*",
                        "timestamp": rng_base + j,
                        "duration": 1000 + j,
                        "localEndpoint": {"serviceName": f"svc{svc}"},
                        "tags": {
                            "component": "proxy",
                            "http.method": "GET",
                            "http.status_code": "200",
                            "http.url": (
                                f"http://svc{svc}.ns.svc.cluster.local"
                                f"/api/{j % 7}"
                            ),
                            "istio.canonical_revision": "v1",
                            "istio.canonical_service": f"svc{svc}",
                            "istio.mesh_id": "cluster.local",
                            "istio.namespace": "ns",
                        },
                    }
                )
            groups.append(g)
        return groups

    prebuilt = [tick_traces(i) for i in range(max(1, ticks))]

    def source(_lb, _t, _lim):
        return prebuilt.pop(0) if prebuilt else []

    dp = DataProcessor(trace_source=source, use_device_stats=True)
    for i in range(max(1, ticks)):
        with TRACER.tick():
            dp.collect(
                {"uniqueId": f"prof{i}", "lookBack": 30_000, "time": i + 1}
            )
    # raw-ingest leg: big enough that the byte-balanced native parse
    # actually fans out to `threads` workers (barrier skew => per-shard
    # lock-wait)
    raw = make_raw_window(
        2000, 20, t_start=seed * 10_000, trace_prefix=f"prof{seed}-"
    )
    with TRACER.tick(root_name="dp-ingest"):
        try:
            dp.ingest_raw_window(raw)
        except ValueError as exc:
            print(f"raw-ingest leg skipped: {exc}", file=sys.stderr)
    profile = report.build_profile()
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(profile, f, indent=1)
    return profile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "artifact",
        nargs="*",
        help="artifact path(s); optionally prefixed by the 'report' verb",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="print the condensed profile JSON instead of the text report",
    )
    ap.add_argument(
        "--diff",
        nargs=2,
        metavar=("BASELINE", "CANDIDATE"),
        help="compare two artifacts' per-phase p95; exit 1 on regression",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=None,
        help="override the default relative regression threshold",
    )
    ap.add_argument(
        "--capture",
        metavar="OUT",
        help="run the seeded synthetic workload and write a profile here",
    )
    ap.add_argument("--ticks", type=int, default=4, help="capture ticks")
    ap.add_argument(
        "--threads",
        type=int,
        default=2,
        help="native parse workers for the capture (2 shows barrier skew)",
    )
    ap.add_argument("--seed", type=int, default=0, help="capture seed")
    args = ap.parse_args(argv)

    from kmamiz_tpu.telemetry.profiling import report

    if args.capture:
        profile = _capture(args.capture, args.ticks, args.threads, args.seed)
        print(report.render(profile), file=sys.stderr)
        print(json.dumps({"profile": args.capture, **{
            k: profile[k] for k in ("ticks", "wall_ms", "attribution_ratio")
        }}))
        return 0

    if args.diff:
        base_doc, cand_doc = (_load(p) for p in args.diff)
        base, cand = (report.from_any(d) for d in (base_doc, cand_doc))
        thresholds = (
            {"default": args.threshold} if args.threshold is not None else None
        )
        regressions = report.diff(base, cand, thresholds=thresholds)
        for r in regressions:
            print(
                f"REGRESSION {r['phase']}: p95 {r['baseline_p95_ms']}ms -> "
                f"{r['candidate_p95_ms']}ms "
                f"(x{r['ratio']}, threshold +{int(r['threshold'] * 100)}%)",
                file=sys.stderr,
            )
        out = {"regressions": regressions}
        blame = _flight_blame(cand_doc, regressions)
        if blame:
            out["blame"] = blame
            print(
                f"BLAME {blame['scenario']}: gate={blame['blamed_gate']} "
                f"phase={blame['blamed_phase']}",
                file=sys.stderr,
            )
        print(json.dumps(out))
        return 1 if regressions else 0

    paths = [p for p in args.artifact if p != "report"]
    if not paths:
        ap.error("nothing to do: pass an artifact, --diff, or --capture")
    for path in paths:
        profile = report.from_any(_load(path))
        if args.json:
            print(json.dumps(profile, indent=1))
        else:
            print(report.render(profile))
    return 0


if __name__ == "__main__":
    sys.exit(main())
