"""Assemble the Envoy proxy-wasm telemetry filter binary from the tree.

The image ships no wasm toolchain (no tinygo for envoy/filter/main.go, no
clang wasm32 target), so this builder emits the filter directly through
tools/wasm_asm.py — pure Python, reproducible, no network. Output:
envoy/filter/kmamiz_filter.wasm, served by the API at GET /wasm
(KMAMIZ_WASM_PATH) and deployed by envoy/EnvoyFilter-WASM.yaml.

Behavior (proxy-wasm ABI 0.2.x, the contract of the reference's Go filter
/root/reference/envoy/wasm/main.go, mirrored by the Go source kept at
envoy/filter/main.go for tinygo-equipped builds):

- on request headers: build and remember the
  `reqId/traceId/spanId/parentSpanId` block per stream context; when the
  request does NOT carry `content-type: application/json`, immediately log
    [Request ids] [METHOD hostpath] (+ " [ContentType ..]")
- JSON requests wait for the body: at the body callback the buffered
  bytes are DESENSITIZED — string values -> "", numbers -> 0, booleans/
  null/containers preserved, object keys kept, ", "/": " separators —
  by a validating single-pass JSON transform, and the line logs with
  " [Body] {..}". Invalid JSON drops the body block (never leaks).
- response headers/body mirror this with [Response ids] [Status] <code>.
- proxy_on_log backstops streams whose expected body never arrived, so
  every stream still emits its line pair.
- ids default to NO_ID individually, method/host/path to "" — exactly
  kmamiz_tpu.core.envoy_filter.format_request_log/format_response_log,
  which tests/test_wasm_filter.py executes this BINARY against (via the
  tools/wasm_interp.py interpreter) to prove.

Known, documented divergences from the Python twin's json.loads/dumps
round trip (tests pin the common cases byte-identically):
- object KEYS are copied verbatim: `\\/`, `\\uXXXX`, and non-ASCII keys
  keep their original spelling instead of json.dumps' normalized form;
- duplicate object keys are kept (the twin's dict round trip dedups to
  the last occurrence);
- NaN/Infinity literals are rejected (json.loads accepts them);
- bodies larger than the transform buffer (24 KB output) drop the block.

Host interface used:
  env.proxy_log(level, ptr, size) -> status
  env.proxy_get_header_map_value(map_type, kptr, klen, out_ptr, out_size)
      -> status            (map_type 0 = request headers, 2 = response)
  env.proxy_get_buffer_bytes(buffer_type, start, length, out_ptr,
      out_size) -> status  (buffer 0 = request body, 1 = response body)

Memory map (4 pages):
  0x0080.. : static strings (data segment)
  0x0800   : header-value out-ptr scratch, 0x0804: out-size scratch
  0x1000.. : log-line build buffer (to 0x8000, clamped)
  0x8000.. : per-stream context table, 128 slots x 256 B
             [0]=ctx_id [4]=flags [8]=req_body_total [12]=resp_body_total
             [16]=ids_len [20..]=ids bytes
  0x10000  : desensitized-body output buffer (24 KB)
  0x16000  : JSON container stack (64 B)
  0x16100..0x40000 : bump arena for proxy_on_memory_allocate (wraps;
             host-written values are consumed within the same callback)
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from wasm_asm import I32, Asm, Module  # noqa: E402

LINE_BUF = 0x1000
OUT_PTR = 0x800
OUT_SIZE = 0x804
CTX_TABLE = 0x8000
CTX_SLOTS = 128
CTX_SLOT_SIZE = 256
# slot field offsets: body totals accumulate across chunked deliveries
# (proxy-wasm delivers bodies in multiple on_*_body calls; the reference
# filter sums bodySize and reads [0, total] at end_of_stream,
# envoy/wasm/main.go:94-117)
SLOT_FLAGS = 4
SLOT_REQ_TOTAL = 8
SLOT_RESP_TOTAL = 12
SLOT_IDS_LEN = 16
SLOT_IDS = 20
IDS_CAP = CTX_SLOT_SIZE - SLOT_IDS
# proxy-wasm action codes returned by body callbacks
ACTION_CONTINUE = 0
ACTION_PAUSE = 1
BODY_BUF = 0x10000
BODY_CAP = 0x6000  # 24 KB transformed-body budget
STACK_BASE = 0x16000
MAX_DEPTH = 64
ARENA_LO = 0x16100
ARENA_HI = 0x40000
LOG_INFO = 2
MAP_REQUEST = 0
MAP_RESPONSE = 2
BUF_REQUEST_BODY = 0
BUF_RESPONSE_BODY = 1

# slot flag bits
F_REQ_LOGGED = 1
F_RESP_LOGGED = 2
F_REQ_PENDING = 4
F_RESP_PENDING = 8

# desens states
ST_VALUE = 0
ST_VALUE_OR_END = 1
ST_KEY_OR_END = 2
ST_KEY = 3
ST_COLON = 4
ST_AFTER = 5


def build() -> bytes:
    m = Module()
    m.set_memory_pages(4)

    # -- static strings ------------------------------------------------------
    strings = {}
    cursor = 0x80

    def S(text: str):
        nonlocal cursor
        if text not in strings:
            raw = text.encode()
            strings[text] = (cursor, len(raw))
            cursor += len(raw)
        return strings[text]

    for s in (
        "x-request-id",
        "x-b3-traceid",
        "x-b3-spanid",
        "x-b3-parentspanid",
        ":method",
        ":authority",
        ":path",
        "content-type",
        ":status",
        "application/json",
        "NO_ID",
        "NO_ID/NO_ID/NO_ID/NO_ID",
        "[Request ",
        "[Response ",
        "] [",
        "] [Status] ",
        " [ContentType ",
        " [Body] ",
        "]",
        "/",
        " ",
        "",
        "true",
        "false",
        "null",
    ):
        S(s)

    # -- imports -------------------------------------------------------------
    LOG = m.add_import("env", "proxy_log", [I32, I32, I32], [I32])
    GET = m.add_import("env", "proxy_get_header_map_value", [I32] * 5, [I32])
    GETBUF = m.add_import("env", "proxy_get_buffer_bytes", [I32] * 5, [I32])

    # -- globals -------------------------------------------------------------
    G_BUMP = m.add_global(ARENA_LO)
    G_LINE = m.add_global(0)
    G_BODY = m.add_global(0)  # desens output length (may exceed cap = fail)

    # -- declarations --------------------------------------------------------
    ALLOC = m.declare_func("alloc", [I32], [I32])
    APPEND = m.declare_func("append", [I32, I32], [])
    MEMCPY = m.declare_func("memcpy", [I32, I32, I32], [])
    MEMEQ = m.declare_func("memeq", [I32, I32, I32], [I32])
    GETHDR = m.declare_func("get_header", [I32, I32, I32], [I32])
    APPVAL = m.declare_func("append_value", [], [])
    APPHDR = m.declare_func("append_header_or", [I32] * 5, [])
    SLOT = m.declare_func("slot", [I32, I32], [I32])
    BODYB = m.declare_func("body_putb", [I32], [])
    BODYPUT = m.declare_func("body_put", [I32, I32], [])
    STRSCAN = m.declare_func("strscan", [I32, I32, I32, I32], [I32])
    HEXOK = m.declare_func("hex_ok", [I32], [I32])
    DESENS = m.declare_func("desens", [I32, I32], [I32])
    BUILDIDS = m.declare_func("build_ids", [I32], [])
    EMITREQ = m.declare_func("emit_req", [I32, I32, I32], [])
    EMITRESP = m.declare_func("emit_resp", [I32, I32, I32], [])
    ONBODY = m.declare_func("on_body", [I32, I32, I32, I32], [I32])
    m.declare_func("proxy_on_memory_allocate", [I32], [I32])
    m.declare_func("proxy_on_request_headers", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_response_headers", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_request_body", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_response_body", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_context_create", [I32, I32], [])
    m.declare_func("proxy_on_vm_start", [I32, I32], [I32])
    m.declare_func("proxy_on_configure", [I32, I32], [I32])
    m.declare_func("proxy_on_done", [I32], [I32])
    m.declare_func("proxy_on_delete", [I32], [])
    m.declare_func("proxy_on_log", [I32], [])
    m.declare_func("proxy_abi_version_0_2_0", [], [])

    def append_lit(a: Asm, text: str) -> None:
        ptr, length = S(text)
        a.i32_const(ptr).i32_const(length).call(APPEND)

    # -- alloc(size) -> ptr | 0 ----------------------------------------------
    a = Asm()
    # a request larger than the whole arena can never be satisfied: return
    # 0 (hosts treat it as allocation failure) instead of handing out a
    # pointer the host's copy would run past linear memory
    a.local_get(0).i32_const(ARENA_HI - ARENA_LO - 16).i32_gt_u().if_()
    a.i32_const(0).return_()
    a.end()
    a.global_get(G_BUMP).local_set(1)
    a.global_get(G_BUMP).local_get(0).i32_add().i32_const(7).i32_add()
    a.i32_const(-8).i32_and().global_set(G_BUMP)
    a.global_get(G_BUMP).i32_const(ARENA_HI).i32_gt_u().if_()
    a.i32_const(ARENA_LO).local_set(1)
    a.i32_const(ARENA_LO).local_get(0).i32_add().i32_const(7).i32_add()
    a.i32_const(-8).i32_and().global_set(G_BUMP)
    a.end()
    a.local_get(1)
    m.define_func("alloc", 1, a)

    # -- append(src, len): into the line buffer, clamped ---------------------
    line_cap = CTX_TABLE - LINE_BUF
    a = Asm()
    a.local_get(1).i32_const(line_cap).global_get(G_LINE).i32_sub()
    a.i32_gt_u().if_()
    a.i32_const(line_cap).global_get(G_LINE).i32_sub().local_set(1)
    a.end()
    a.i32_const(0).local_set(2)
    a.block()
    a.loop()
    a.local_get(2).local_get(1).i32_ge_u().br_if(1)
    a.i32_const(LINE_BUF).global_get(G_LINE).i32_add().local_get(2).i32_add()
    a.local_get(0).local_get(2).i32_add().i32_load8_u()
    a.i32_store8()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(0)
    a.end()
    a.end()
    a.global_get(G_LINE).local_get(1).i32_add().global_set(G_LINE)
    m.define_func("append", 1, a)

    # -- memcpy(dst, src, len) ------------------------------------------------
    a = Asm()
    a.i32_const(0).local_set(3)
    a.block()
    a.loop()
    a.local_get(3).local_get(2).i32_ge_u().br_if(1)
    a.local_get(0).local_get(3).i32_add()
    a.local_get(1).local_get(3).i32_add().i32_load8_u()
    a.i32_store8()
    a.local_get(3).i32_const(1).i32_add().local_set(3)
    a.br(0)
    a.end()
    a.end()
    m.define_func("memcpy", 1, a)

    # -- memeq(p1, p2, len) -> i32 -------------------------------------------
    a = Asm()
    a.i32_const(0).local_set(3)
    a.block()
    a.loop()
    a.local_get(3).local_get(2).i32_ge_u().br_if(1)
    a.local_get(0).local_get(3).i32_add().i32_load8_u()
    a.local_get(1).local_get(3).i32_add().i32_load8_u()
    a.i32_ne().if_()
    a.i32_const(0).return_()
    a.end()
    a.local_get(3).i32_const(1).i32_add().local_set(3)
    a.br(0)
    a.end()
    a.end()
    a.i32_const(1)
    m.define_func("memeq", 1, a)

    # -- get_header(map, kptr, klen) -> found ---------------------------------
    a = Asm()
    a.i32_const(OUT_PTR).i32_const(0).i32_store()
    a.i32_const(OUT_SIZE).i32_const(0).i32_store()
    a.local_get(0).local_get(1).local_get(2)
    a.i32_const(OUT_PTR).i32_const(OUT_SIZE).call(GET)
    a.if_(I32)
    a.i32_const(0)
    a.else_()
    a.i32_const(OUT_PTR).i32_load().i32_eqz().if_(I32)
    a.i32_const(0)
    a.else_()
    a.i32_const(OUT_SIZE).i32_load().i32_const(0).i32_gt_u()
    a.end()
    a.end()
    m.define_func("get_header", 0, a)

    # -- append_value() -------------------------------------------------------
    a = Asm()
    a.i32_const(OUT_PTR).i32_load().i32_const(OUT_SIZE).i32_load().call(APPEND)
    m.define_func("append_value", 0, a)

    # -- append_header_or(map, kptr, klen, fbptr, fblen) ----------------------
    a = Asm()
    a.local_get(0).local_get(1).local_get(2).call(GETHDR)
    a.if_()
    a.call(APPVAL)
    a.else_()
    a.local_get(3).local_get(4).call(APPEND)
    a.end()
    m.define_func("append_header_or", 0, a)

    # -- slot(ctx, create) -> addr | 0  (tombstone deletes) -------------------
    TOMB = -1
    a = Asm()
    # locals: 2=h, 3=tries, 4=addr, 5=id, 6=first_tombstone
    a.local_get(0).i32_const(-1640531527).i32_mul()
    a.i32_const(16).i32_shr_u().i32_const(CTX_SLOTS - 1).i32_and()
    a.local_set(2)
    a.i32_const(0).local_set(3)
    a.i32_const(0).local_set(6)
    a.block()
    a.loop()
    a.local_get(3).i32_const(CTX_SLOTS).i32_ge_u().br_if(1)
    a.i32_const(CTX_TABLE).local_get(2).i32_const(CTX_SLOT_SIZE).i32_mul()
    a.i32_add().local_set(4)
    a.local_get(4).i32_load().local_set(5)
    a.local_get(5).local_get(0).i32_eq().if_()
    a.local_get(4).return_()
    a.end()
    a.local_get(5).i32_const(TOMB).i32_eq().if_()
    a.local_get(6).i32_eqz().if_()
    a.local_get(4).local_set(6)
    a.end()
    a.else_()
    a.local_get(5).i32_eqz().if_()
    a.local_get(1).i32_eqz().if_()
    a.i32_const(0).return_()
    a.end()
    a.local_get(6).if_()
    a.local_get(6).local_set(4)
    a.end()
    a.local_get(4).local_get(0).i32_store()
    a.local_get(4).i32_const(0).i32_store(SLOT_FLAGS)
    a.local_get(4).i32_const(0).i32_store(SLOT_REQ_TOTAL)
    a.local_get(4).i32_const(0).i32_store(SLOT_RESP_TOTAL)
    a.local_get(4).i32_const(0).i32_store(SLOT_IDS_LEN)
    a.local_get(4).return_()
    a.end()
    a.end()
    a.local_get(2).i32_const(1).i32_add().i32_const(CTX_SLOTS - 1).i32_and()
    a.local_set(2)
    a.local_get(3).i32_const(1).i32_add().local_set(3)
    a.br(0)
    a.end()
    a.end()
    a.local_get(1).if_()
    a.local_get(6).if_()
    a.local_get(6).local_get(0).i32_store()
    a.local_get(6).i32_const(0).i32_store(SLOT_FLAGS)
    a.local_get(6).i32_const(0).i32_store(SLOT_REQ_TOTAL)
    a.local_get(6).i32_const(0).i32_store(SLOT_RESP_TOTAL)
    a.local_get(6).i32_const(0).i32_store(SLOT_IDS_LEN)
    a.local_get(6).return_()
    a.end()
    a.end()
    a.i32_const(0)
    m.define_func("slot", 5, a)

    # -- body_putb(byte): into BODY_BUF; length may exceed cap (=> fail) -----
    a = Asm()
    a.global_get(G_BODY).i32_const(BODY_CAP).i32_lt_u().if_()
    a.i32_const(BODY_BUF).global_get(G_BODY).i32_add()
    a.local_get(0).i32_store8()
    a.end()
    a.global_get(G_BODY).i32_const(1).i32_add().global_set(G_BODY)
    m.define_func("body_putb", 0, a)

    # -- body_put(src, len) ---------------------------------------------------
    a = Asm()
    a.i32_const(0).local_set(2)
    a.block()
    a.loop()
    a.local_get(2).local_get(1).i32_ge_u().br_if(1)
    a.local_get(0).local_get(2).i32_add().i32_load8_u().call(BODYB)
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(0)
    a.end()
    a.end()
    m.define_func("body_put", 1, a)

    # -- hex_ok(c) -> i32 -----------------------------------------------------
    a = Asm()
    a.local_get(0).i32_const(ord("0")).i32_ge_u()
    a.local_get(0).i32_const(ord("9")).i32_le_u().i32_and().if_()
    a.i32_const(1).return_()
    a.end()
    a.local_get(0).i32_const(0x20).i32_or().local_set(0)  # tolower
    a.local_get(0).i32_const(ord("a")).i32_ge_u()
    a.local_get(0).i32_const(ord("f")).i32_le_u().i32_and()
    m.define_func("hex_ok", 0, a)

    # -- strscan(src, len, p, emit) -> new p past closing quote | -1 ---------
    # p sits just after the opening quote. emit=1 copies the raw bytes
    # (incl. the closing quote) via body_put; emit=0 skips. Validates
    # escapes and rejects raw control characters, like json.loads.
    a = Asm()
    # locals: 4=c, 5=n
    a.block()
    a.loop()
    a.local_get(2).local_get(1).i32_ge_u().br_if(1)  # EOF inside string
    a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(4)
    a.local_get(4).i32_const(ord('"')).i32_eq().if_()
    a.local_get(3).if_()
    a.i32_const(ord('"')).call(BODYB)
    a.end()
    a.local_get(2).i32_const(1).i32_add().return_()
    a.end()
    a.local_get(4).i32_const(ord("\\")).i32_eq().if_()
    a.local_get(2).i32_const(1).i32_add().local_get(1).i32_ge_u().if_()
    a.i32_const(-1).return_()
    a.end()
    a.local_get(0).local_get(2).i32_add().i32_load8_u(1).local_set(5)
    a.local_get(5).i32_const(ord("u")).i32_eq().if_()
    # need p+2..p+5 in bounds: p+6 <= len
    a.local_get(2).i32_const(6).i32_add().local_get(1).i32_gt_u().if_()
    a.i32_const(-1).return_()
    a.end()
    for off in (2, 3, 4, 5):
        a.local_get(0).local_get(2).i32_add().i32_load8_u(off).call(HEXOK)
        a.i32_eqz().if_()
        a.i32_const(-1).return_()
        a.end()
    a.local_get(3).if_()
    a.local_get(0).local_get(2).i32_add().i32_const(6).call(BODYPUT)
    a.end()
    a.local_get(2).i32_const(6).i32_add().local_set(2)
    a.else_()
    # one-char escapes: " \ / b f n r t
    valid = [ord(ch) for ch in '"\\/bfnrt']
    a.i32_const(0).local_set(4)
    for ch in valid:
        a.local_get(5).i32_const(ch).i32_eq().if_()
        a.i32_const(1).local_set(4)
        a.end()
    a.local_get(4).i32_eqz().if_()
    a.i32_const(-1).return_()
    a.end()
    a.local_get(3).if_()
    a.local_get(0).local_get(2).i32_add().i32_const(2).call(BODYPUT)
    a.end()
    a.local_get(2).i32_const(2).i32_add().local_set(2)
    a.end()
    a.else_()
    a.local_get(4).i32_const(0x20).i32_lt_u().if_()  # raw control char
    a.i32_const(-1).return_()
    a.end()
    a.local_get(3).if_()
    a.local_get(4).call(BODYB)
    a.end()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.end()
    a.br(0)
    a.end()
    a.end()
    a.i32_const(-1)
    m.define_func("strscan", 2, a)

    # -- desens(src, len) -> ok ----------------------------------------------
    # single-pass validate + transform: string values -> "", numbers -> 0,
    # keys/booleans/null/structure copied, ", " and ": " separators.
    a = Asm()
    # locals: 2=p, 3=state, 4=depth, 5=c, 6=q
    a.i32_const(0).global_set(G_BODY)
    a.i32_const(0).local_set(2)
    a.i32_const(ST_VALUE).local_set(3)
    a.i32_const(0).local_set(4)
    a.block()
    a.loop()
    a.local_get(2).local_get(1).i32_ge_u().br_if(1)
    a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(5)
    # whitespace
    a.i32_const(0).local_set(6)
    for ws in (0x20, 0x09, 0x0A, 0x0D):
        a.local_get(5).i32_const(ws).i32_eq().if_()
        a.i32_const(1).local_set(6)
        a.end()
    a.local_get(6).if_()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(1)  # continue main loop
    a.end()

    # '"'
    a.local_get(5).i32_const(ord('"')).i32_eq().if_()
    a.local_get(3).i32_const(-2).i32_and().i32_const(2).i32_eq().if_()
    # key states (2,3): copy verbatim
    a.i32_const(ord('"')).call(BODYB)
    a.local_get(0).local_get(1).local_get(2).i32_const(1).i32_add()
    a.i32_const(1).call(STRSCAN).local_set(2)
    a.local_get(2).i32_const(-1).i32_eq().if_()
    a.i32_const(0).return_()
    a.end()
    a.i32_const(ST_COLON).local_set(3)
    a.br(2)  # continue
    a.end()
    a.local_get(3).i32_const(ST_VALUE_OR_END).i32_le_u().if_()
    # string value -> ""
    a.local_get(0).local_get(1).local_get(2).i32_const(1).i32_add()
    a.i32_const(0).call(STRSCAN).local_set(2)
    a.local_get(2).i32_const(-1).i32_eq().if_()
    a.i32_const(0).return_()
    a.end()
    a.i32_const(ord('"')).call(BODYB)
    a.i32_const(ord('"')).call(BODYB)
    a.i32_const(ST_AFTER).local_set(3)
    a.br(2)
    a.end()
    a.i32_const(0).return_()
    a.end()

    # '{' / '['
    for ch, kind, nstate in ((ord("{"), 1, ST_KEY_OR_END), (ord("["), 2, ST_VALUE_OR_END)):
        a.local_get(5).i32_const(ch).i32_eq().if_()
        a.local_get(3).i32_const(ST_VALUE_OR_END).i32_gt_u().if_()
        a.i32_const(0).return_()
        a.end()
        a.local_get(4).i32_const(MAX_DEPTH).i32_ge_u().if_()
        a.i32_const(0).return_()
        a.end()
        a.i32_const(STACK_BASE).local_get(4).i32_add()
        a.i32_const(kind).i32_store8()
        a.local_get(4).i32_const(1).i32_add().local_set(4)
        a.i32_const(ch).call(BODYB)
        a.i32_const(nstate).local_set(3)
        a.local_get(2).i32_const(1).i32_add().local_set(2)
        a.br(1)
        a.end()

    # '}' / ']'
    for ch, kind, open_state in ((ord("}"), 1, ST_KEY_OR_END), (ord("]"), 2, ST_VALUE_OR_END)):
        a.local_get(5).i32_const(ch).i32_eq().if_()
        # allowed: state==open_state (empty container), or state==AFTER
        # with a matching container on the stack
        a.i32_const(0).local_set(6)
        a.local_get(3).i32_const(open_state).i32_eq().if_()
        a.i32_const(1).local_set(6)
        a.end()
        a.local_get(3).i32_const(ST_AFTER).i32_eq().if_()
        a.i32_const(1).local_set(6)
        a.end()
        a.local_get(6).i32_eqz().if_()
        a.i32_const(0).return_()
        a.end()
        a.local_get(4).i32_eqz().if_()
        a.i32_const(0).return_()
        a.end()
        a.i32_const(STACK_BASE).local_get(4).i32_const(1).i32_sub().i32_add()
        a.i32_load8_u().i32_const(kind).i32_ne().if_()
        a.i32_const(0).return_()
        a.end()
        a.local_get(4).i32_const(1).i32_sub().local_set(4)
        a.i32_const(ch).call(BODYB)
        a.i32_const(ST_AFTER).local_set(3)
        a.local_get(2).i32_const(1).i32_add().local_set(2)
        a.br(1)
        a.end()

    # ','
    a.local_get(5).i32_const(ord(",")).i32_eq().if_()
    a.local_get(3).i32_const(ST_AFTER).i32_ne().if_()
    a.i32_const(0).return_()
    a.end()
    a.local_get(4).i32_eqz().if_()
    a.i32_const(0).return_()
    a.end()
    a.i32_const(ord(",")).call(BODYB)
    a.i32_const(ord(" ")).call(BODYB)
    a.i32_const(STACK_BASE).local_get(4).i32_const(1).i32_sub().i32_add()
    a.i32_load8_u().i32_const(1).i32_eq().if_()
    a.i32_const(ST_KEY).local_set(3)
    a.else_()
    a.i32_const(ST_VALUE).local_set(3)
    a.end()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(1)
    a.end()

    # ':'
    a.local_get(5).i32_const(ord(":")).i32_eq().if_()
    a.local_get(3).i32_const(ST_COLON).i32_ne().if_()
    a.i32_const(0).return_()
    a.end()
    a.i32_const(ord(":")).call(BODYB)
    a.i32_const(ord(" ")).call(BODYB)
    a.i32_const(ST_VALUE).local_set(3)
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(1)
    a.end()

    # literals / numbers: value states only
    a.local_get(3).i32_const(ST_VALUE_OR_END).i32_gt_u().if_()
    a.i32_const(0).return_()
    a.end()
    for lit in ("true", "false", "null"):
        lp, ll = S(lit)
        a.local_get(5).i32_const(ord(lit[0])).i32_eq().if_()
        a.local_get(2).i32_const(ll).i32_add().local_get(1).i32_gt_u().if_()
        a.i32_const(0).return_()
        a.end()
        a.local_get(0).local_get(2).i32_add().i32_const(lp).i32_const(ll)
        a.call(MEMEQ).i32_eqz().if_()
        a.i32_const(0).return_()
        a.end()
        a.i32_const(lp).i32_const(ll).call(BODYPUT)
        a.local_get(2).i32_const(ll).i32_add().local_set(2)
        a.i32_const(ST_AFTER).local_set(3)
        a.br(1)
        a.end()
    # number
    a.i32_const(0).local_set(6)  # digit seen
    a.local_get(5).i32_const(ord("-")).i32_eq().if_()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.end()
    # integer part: first digit, leading-zero rule
    a.local_get(2).local_get(1).i32_ge_u().if_()
    a.i32_const(0).return_()
    a.end()
    a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(5)
    a.local_get(5).i32_const(ord("0")).i32_lt_u()
    a.local_get(5).i32_const(ord("9")).i32_gt_u().i32_or().if_()
    a.i32_const(0).return_()
    a.end()
    a.local_get(5).i32_const(ord("0")).i32_eq().if_()
    # "0" must not be followed by another digit
    a.local_get(2).i32_const(1).i32_add().local_get(1).i32_lt_u().if_()
    a.local_get(0).local_get(2).i32_add().i32_load8_u(1).local_set(6)
    a.local_get(6).i32_const(ord("0")).i32_ge_u()
    a.local_get(6).i32_const(ord("9")).i32_le_u().i32_and().if_()
    a.i32_const(0).return_()
    a.end()
    a.end()
    a.end()
    # consume digits

    def consume_digits(require: bool) -> None:
        if require:
            a.local_get(2).local_get(1).i32_ge_u().if_()
            a.i32_const(0).return_()
            a.end()
            a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(5)
            a.local_get(5).i32_const(ord("0")).i32_lt_u()
            a.local_get(5).i32_const(ord("9")).i32_gt_u().i32_or().if_()
            a.i32_const(0).return_()
            a.end()
        a.block()
        a.loop()
        a.local_get(2).local_get(1).i32_ge_u().br_if(1)
        a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(5)
        a.local_get(5).i32_const(ord("0")).i32_lt_u()
        a.local_get(5).i32_const(ord("9")).i32_gt_u().i32_or().br_if(1)
        a.local_get(2).i32_const(1).i32_add().local_set(2)
        a.br(0)
        a.end()
        a.end()

    consume_digits(require=False)
    # fraction
    a.local_get(2).local_get(1).i32_lt_u().if_()
    a.local_get(0).local_get(2).i32_add().i32_load8_u().i32_const(ord(".")).i32_eq().if_()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    consume_digits(require=True)
    a.end()
    a.end()
    # exponent
    a.local_get(2).local_get(1).i32_lt_u().if_()
    a.local_get(0).local_get(2).i32_add().i32_load8_u().i32_const(0x20).i32_or()
    a.i32_const(ord("e")).i32_eq().if_()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.local_get(2).local_get(1).i32_lt_u().if_()
    a.local_get(0).local_get(2).i32_add().i32_load8_u().local_set(5)
    a.local_get(5).i32_const(ord("+")).i32_eq()
    a.local_get(5).i32_const(ord("-")).i32_eq().i32_or().if_()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.end()
    a.end()
    consume_digits(require=True)
    a.end()
    a.end()
    a.i32_const(ord("0")).call(BODYB)
    a.i32_const(ST_AFTER).local_set(3)
    a.br(0)  # continue main loop
    a.end()  # loop
    a.end()  # block
    # accept iff one complete value and the output fit the buffer
    a.local_get(3).i32_const(ST_AFTER).i32_eq()
    a.local_get(4).i32_eqz().i32_and()
    a.global_get(G_BODY).i32_const(BODY_CAP).i32_le_u().i32_and()
    m.define_func("desens", 5, a)

    # -- build_ids(ctx): snapshot the id block into the slot at request-
    # header time (the only moment the values are guaranteed current);
    # uses the line buffer as scratch
    no_id = S("NO_ID")
    empty = S("")

    def append_ids_from_headers(a: Asm) -> None:
        # the one id-block definition (req/trace/span/parent + slashes)
        # shared by build_ids and both emit fallbacks
        for i, key in enumerate(
            ("x-request-id", "x-b3-traceid", "x-b3-spanid", "x-b3-parentspanid")
        ):
            kp, kl = S(key)
            a.i32_const(MAP_REQUEST).i32_const(kp).i32_const(kl)
            a.i32_const(no_id[0]).i32_const(no_id[1]).call(APPHDR)
            if i < 3:
                append_lit(a, "/")

    a = Asm()
    # locals: 1=ids_len, 2=slot_addr
    a.i32_const(0).global_set(G_LINE)
    append_ids_from_headers(a)
    a.global_get(G_LINE).local_set(1)
    a.local_get(0).i32_const(1).call(SLOT).local_set(2)
    a.local_get(2).if_()
    a.local_get(1).i32_const(IDS_CAP).i32_gt_u().if_()
    a.i32_const(IDS_CAP).local_set(1)
    a.end()
    a.local_get(2).local_get(1).i32_store(SLOT_IDS_LEN)
    a.local_get(2).i32_const(SLOT_IDS).i32_add()
    a.i32_const(LINE_BUF).local_get(1).call(MEMCPY)
    a.end()
    m.define_func("build_ids", 2, a)

    # -- emit_req(ctx, body_ptr, body_len) ------------------------------------
    a = Asm()
    # locals: 3=slot_addr
    a.i32_const(0).global_set(G_LINE)
    append_lit(a, "[Request ")
    a.local_get(0).i32_const(0).call(SLOT).local_set(3)
    a.local_get(3).if_(I32)
    a.local_get(3).i32_load(SLOT_IDS_LEN).i32_const(0).i32_gt_u()
    a.else_()
    a.i32_const(0)
    a.end()
    a.if_()
    a.local_get(3).i32_const(SLOT_IDS).i32_add().local_get(3).i32_load(SLOT_IDS_LEN).call(APPEND)
    a.else_()
    append_ids_from_headers(a)
    a.end()
    append_lit(a, "] [")
    for key in (":method", None, ":authority", ":path"):
        if key is None:
            append_lit(a, " ")
            continue
        kp, kl = S(key)
        a.i32_const(MAP_REQUEST).i32_const(kp).i32_const(kl)
        a.i32_const(empty[0]).i32_const(empty[1]).call(APPHDR)
    append_lit(a, "]")
    ct = S("content-type")
    a.i32_const(MAP_REQUEST).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_()
    append_lit(a, " [ContentType ")
    a.call(APPVAL)
    append_lit(a, "]")
    a.end()
    a.local_get(2).if_()
    append_lit(a, " [Body] ")
    a.local_get(1).local_get(2).call(APPEND)
    a.end()
    a.i32_const(LOG_INFO).i32_const(LINE_BUF).global_get(G_LINE).call(LOG)
    a.drop()
    # mark logged
    a.local_get(3).if_()
    a.local_get(3).local_get(3).i32_load(4).i32_const(F_REQ_LOGGED).i32_or()
    a.i32_store(4)
    a.end()
    m.define_func("emit_req", 1, a)

    # -- emit_resp(ctx, body_ptr, body_len) -----------------------------------
    a = Asm()
    # locals: 3=slot_addr
    a.i32_const(0).global_set(G_LINE)
    append_lit(a, "[Response ")
    a.local_get(0).i32_const(0).call(SLOT).local_set(3)
    a.local_get(3).if_(I32)
    a.local_get(3).i32_load(SLOT_IDS_LEN).i32_const(0).i32_gt_u()
    a.else_()
    a.i32_const(0)
    a.end()
    a.if_()
    a.local_get(3).i32_const(SLOT_IDS).i32_add().local_get(3).i32_load(SLOT_IDS_LEN).call(APPEND)
    a.else_()
    # no stored ids (no slot, or a JSON request whose line is still
    # pending): rebuild from the request header map, which proxy-wasm
    # keeps accessible through the response phase
    append_ids_from_headers(a)
    a.end()
    append_lit(a, "] [Status] ")
    st = S(":status")
    a.i32_const(MAP_RESPONSE).i32_const(st[0]).i32_const(st[1])
    a.i32_const(empty[0]).i32_const(empty[1]).call(APPHDR)
    ct = S("content-type")
    a.i32_const(MAP_RESPONSE).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_()
    append_lit(a, " [ContentType ")
    a.call(APPVAL)
    append_lit(a, "]")
    a.end()
    a.local_get(2).if_()
    append_lit(a, " [Body] ")
    a.local_get(1).local_get(2).call(APPEND)
    a.end()
    a.i32_const(LOG_INFO).i32_const(LINE_BUF).global_get(G_LINE).call(LOG)
    a.drop()
    a.local_get(3).if_()
    a.local_get(3).local_get(3).i32_load(4).i32_const(F_RESP_LOGGED).i32_or()
    a.i32_store(4)
    a.end()
    m.define_func("emit_resp", 1, a)

    # -- on_body(ctx, size, eos, is_response) -> action -----------------------
    # shared body-callback logic, mirroring the reference filter
    # (envoy/wasm/main.go:94-143): accumulate this delivery's size into
    # the slot, return Pause until end_of_stream so the host buffers the
    # whole body, then read the full buffered range [0, total],
    # desensitize, and emit the pending line (with the body block when
    # the transform succeeded, without it otherwise)
    a = Asm()
    # locals: 4=slot_addr, 5=flags, 6=src, 7=ok, 8=total_addr
    a.local_get(0).i32_const(0).call(SLOT).local_set(4)
    a.local_get(4).i32_eqz().if_()
    # no context (no request headers seen): never pause such a stream
    a.i32_const(ACTION_CONTINUE).return_()
    a.end()
    # total += this delivery's size (field picked by direction)
    a.local_get(4)
    a.local_get(3).if_(I32)
    a.i32_const(SLOT_RESP_TOTAL)
    a.else_()
    a.i32_const(SLOT_REQ_TOTAL)
    a.end()
    a.i32_add().local_set(8)
    a.local_get(8).local_get(8).i32_load().local_get(1).i32_add().i32_store()
    a.local_get(2).i32_eqz().if_()
    # wait until the entire body is buffered (main.go:101-104)
    a.i32_const(ACTION_PAUSE).return_()
    a.end()
    a.local_get(4).i32_load(4).local_set(5)
    # pending/logged bit pair for this direction
    a.local_get(3).if_(I32)
    a.i32_const(F_RESP_PENDING)
    a.else_()
    a.i32_const(F_REQ_PENDING)
    a.end()
    a.local_get(5).i32_and().i32_eqz().if_()
    a.i32_const(ACTION_CONTINUE).return_()  # no JSON body expected
    a.end()
    a.local_get(3).if_(I32)
    a.i32_const(F_RESP_LOGGED)
    a.else_()
    a.i32_const(F_REQ_LOGGED)
    a.end()
    a.local_get(5).i32_and().if_()
    a.i32_const(ACTION_CONTINUE).return_()  # already logged
    a.end()
    # fetch the WHOLE buffered body [0, total]
    a.i32_const(OUT_PTR).i32_const(0).i32_store()
    a.i32_const(OUT_SIZE).i32_const(0).i32_store()
    a.local_get(3).if_(I32)
    a.i32_const(BUF_RESPONSE_BODY)
    a.else_()
    a.i32_const(BUF_REQUEST_BODY)
    a.end()
    a.i32_const(0).local_get(8).i32_load()
    a.i32_const(OUT_PTR).i32_const(OUT_SIZE).call(GETBUF)
    a.if_(I32)
    a.i32_const(0)
    a.else_()
    a.i32_const(OUT_PTR).i32_load().i32_const(0).i32_ne()
    a.end()
    a.local_set(7)
    a.i32_const(0).local_set(6)
    a.local_get(7).if_()
    a.i32_const(OUT_PTR).i32_load().local_set(6)
    a.local_get(6).i32_const(OUT_SIZE).i32_load().call(DESENS).local_set(7)
    a.end()
    # emit with/without body
    a.local_get(7).if_()
    a.local_get(3).if_()
    a.local_get(0).i32_const(BODY_BUF).global_get(G_BODY).call(EMITRESP)
    a.else_()
    a.local_get(0).i32_const(BODY_BUF).global_get(G_BODY).call(EMITREQ)
    a.end()
    a.else_()
    a.local_get(3).if_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITRESP)
    a.else_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITREQ)
    a.end()
    a.end()
    a.i32_const(ACTION_CONTINUE)
    m.define_func("on_body", 5, a)

    # -- ABI surface ----------------------------------------------------------
    a = Asm()
    a.local_get(0).call(ALLOC)
    m.define_func("proxy_on_memory_allocate", 0, a)

    appjson = S("application/json")

    a = Asm()
    # locals: 3=slot_addr
    ct = S("content-type")
    a.i32_const(MAP_REQUEST).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_(I32)
    a.i32_const(OUT_SIZE).i32_load().i32_const(appjson[1]).i32_eq().if_(I32)
    a.i32_const(OUT_PTR).i32_load().i32_const(appjson[0])
    a.i32_const(appjson[1]).call(MEMEQ)
    a.else_()
    a.i32_const(0)
    a.end()
    a.else_()
    a.i32_const(0)
    a.end()
    a.if_()
    # JSON request: snapshot ids now, log later (at body end or on_log).
    # A full context table means no pending flag can be tracked: log at
    # headers immediately (body block lost, line pair kept)
    a.local_get(0).call(BUILDIDS)
    a.local_get(0).i32_const(0).call(SLOT).local_tee(3).if_()
    a.local_get(3).local_get(3).i32_load(4).i32_const(F_REQ_PENDING).i32_or()
    a.i32_store(4)
    a.else_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITREQ)
    a.end()
    a.else_()
    a.local_get(0).call(BUILDIDS)
    a.local_get(0).i32_const(0).i32_const(0).call(EMITREQ)
    a.end()
    a.i32_const(0)
    m.define_func("proxy_on_request_headers", 1, a)

    a = Asm()
    # locals: 3=slot_addr
    a.i32_const(MAP_RESPONSE).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_(I32)
    a.i32_const(OUT_SIZE).i32_load().i32_const(appjson[1]).i32_eq().if_(I32)
    a.i32_const(OUT_PTR).i32_load().i32_const(appjson[0])
    a.i32_const(appjson[1]).call(MEMEQ)
    a.else_()
    a.i32_const(0)
    a.end()
    a.else_()
    a.i32_const(0)
    a.end()
    a.if_()
    a.local_get(0).i32_const(1).call(SLOT).local_tee(3).if_()
    a.local_get(3).local_get(3).i32_load(4).i32_const(F_RESP_PENDING).i32_or()
    a.i32_store(4)
    a.else_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITRESP)  # table full
    a.end()
    a.else_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITRESP)
    a.end()
    a.i32_const(0)
    m.define_func("proxy_on_response_headers", 1, a)

    a = Asm()
    a.local_get(0).local_get(1).local_get(2).i32_const(0).call(ONBODY)
    m.define_func("proxy_on_request_body", 0, a)

    a = Asm()
    a.local_get(0).local_get(1).local_get(2).i32_const(1).call(ONBODY)
    m.define_func("proxy_on_response_body", 0, a)

    m.define_func("proxy_on_context_create", 0, Asm())

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_vm_start", 0, a)

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_configure", 0, a)

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_done", 0, a)

    a = Asm()
    a.local_get(0).i32_const(0).call(SLOT).local_tee(1).if_()
    a.local_get(1).i32_const(-1).i32_store()  # tombstone, not empty
    a.end()
    m.define_func("proxy_on_delete", 1, a)

    # proxy_on_log: backstop for streams whose expected JSON body never
    # arrived — emit the pending line(s) without a body block so every
    # stream still produces its pair
    a = Asm()
    # locals: 1=slot_addr, 2=flags
    a.local_get(0).i32_const(0).call(SLOT).local_tee(1).i32_eqz().if_()
    a.return_()
    a.end()
    a.local_get(1).i32_load(4).local_set(2)
    a.local_get(2).i32_const(F_REQ_PENDING).i32_and().if_()
    a.local_get(2).i32_const(F_REQ_LOGGED).i32_and().i32_eqz().if_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITREQ)
    a.end()
    a.end()
    a.local_get(1).i32_load(4).local_set(2)
    a.local_get(2).i32_const(F_RESP_PENDING).i32_and().if_()
    a.local_get(2).i32_const(F_RESP_LOGGED).i32_and().i32_eqz().if_()
    a.local_get(0).i32_const(0).i32_const(0).call(EMITRESP)
    a.end()
    a.end()
    m.define_func("proxy_on_log", 2, a)

    m.define_func("proxy_abi_version_0_2_0", 0, Asm())

    for name in (
        "proxy_on_memory_allocate",
        "proxy_on_request_headers",
        "proxy_on_response_headers",
        "proxy_on_request_body",
        "proxy_on_response_body",
        "proxy_on_context_create",
        "proxy_on_vm_start",
        "proxy_on_configure",
        "proxy_on_done",
        "proxy_on_delete",
        "proxy_on_log",
        "proxy_abi_version_0_2_0",
    ):
        m.export_func(name)
    m.export_func("malloc", "alloc")  # legacy hosts allocate via malloc
    m.export_memory()

    base = min(off for off, _ in strings.values())
    end = max(off + ln for off, ln in strings.values())
    blob = bytearray(end - base)
    for text, (off, ln) in strings.items():
        blob[off - base : off - base + ln] = text.encode()
    m.add_data(base, bytes(blob))

    return m.build()


def main() -> None:
    out = Path(__file__).resolve().parent.parent / "envoy" / "filter" / "kmamiz_filter.wasm"
    binary = build()
    out.write_bytes(binary)
    print(f"wrote {out} ({len(binary)} bytes)")


if __name__ == "__main__":
    main()
