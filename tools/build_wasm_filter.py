"""Assemble the Envoy proxy-wasm telemetry filter binary from the tree.

The image ships no wasm toolchain (no tinygo for envoy/filter/main.go, no
clang wasm32 target), so this builder emits the filter directly through
tools/wasm_asm.py — pure Python, reproducible, no network. Output:
envoy/filter/kmamiz_filter.wasm, served by the API at GET /wasm
(KMAMIZ_WASM_PATH) and deployed by envoy/EnvoyFilter-WASM.yaml.

Behavior (proxy-wasm ABI 0.2.x, the contract of the reference's Go filter
/root/reference/envoy/wasm/main.go and of the richer Go source kept at
envoy/filter/main.go for tinygo-equipped builds):

- on request headers: log
    [Request reqId/traceId/spanId/parentSpanId] [METHOD hostpath]
    (+ " [ContentType ..]" when the request carries one)
  and remember the id block per stream context.
- on response headers: log
    [Response <same ids>] [Status] <code> (+ ContentType block)
- ids default to NO_ID individually, method/host/path to "" — exactly
  kmamiz_tpu.core.envoy_filter.format_request_log/format_response_log,
  which tests/test_wasm_filter.py executes this BINARY against (via the
  tools/wasm_interp.py interpreter) to prove.

Body capture/desensitization is the one main.go feature not assembled
here (it needs a JSON tokenizer in raw wasm); the ingestion parser
accepts body-less lines, so schemas come from the Go build when a tinygo
toolchain exists. Everything else — the lines every scorer, dependency
graph, and insight consumes — is produced by this in-tree artifact.

Host interface used:
  env.proxy_log(level, ptr, size) -> status
  env.proxy_get_header_map_value(map_type, kptr, klen, out_ptr, out_size)
      -> status            (map_type 0 = request headers, 2 = response)

Memory map (4 pages):
  0x0080.. : static strings (data segment)
  0x0800   : header-value out-ptr scratch, 0x0804: out-size scratch
  0x1000.. : log-line build buffer
  0x8000.. : per-stream context table, 128 slots x 256 B
             [0]=ctx_id [4]=ids_len [8..]=ids bytes
  0x10000..0x40000 : bump arena for proxy_on_memory_allocate (wraps;
             host-written values are consumed within the same callback)
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from wasm_asm import I32, Asm, Module  # noqa: E402

LINE_BUF = 0x1000
OUT_PTR = 0x800
OUT_SIZE = 0x804
CTX_TABLE = 0x8000
CTX_SLOTS = 128
CTX_SLOT_SIZE = 256
IDS_CAP = CTX_SLOT_SIZE - 8
ARENA_LO = 0x10000
ARENA_HI = 0x40000
LOG_INFO = 2
MAP_REQUEST = 0
MAP_RESPONSE = 2


def build() -> bytes:
    m = Module()
    m.set_memory_pages(4)

    # -- static strings ------------------------------------------------------
    strings = {}
    cursor = 0x80

    def S(text: str):
        nonlocal cursor
        if text not in strings:
            raw = text.encode()
            strings[text] = (cursor, len(raw))
            cursor += len(raw)
        return strings[text]

    for s in (
        "x-request-id",
        "x-b3-traceid",
        "x-b3-spanid",
        "x-b3-parentspanid",
        ":method",
        ":authority",
        ":path",
        "content-type",
        ":status",
        "NO_ID",
        "NO_ID/NO_ID/NO_ID/NO_ID",
        "[Request ",
        "[Response ",
        "] [",
        "] [Status] ",
        " [ContentType ",
        "]",
        "/",
        " ",
        "",
    ):
        S(s)

    # -- imports (function index space starts with these) --------------------
    LOG = m.add_import("env", "proxy_log", [I32, I32, I32], [I32])
    GET = m.add_import(
        "env", "proxy_get_header_map_value", [I32] * 5, [I32]
    )

    # -- globals -------------------------------------------------------------
    G_BUMP = m.add_global(ARENA_LO)
    G_LINE = m.add_global(0)

    # -- function declarations (bodies reference forward indices) ------------
    ALLOC = m.declare_func("alloc", [I32], [I32])
    APPEND = m.declare_func("append", [I32, I32], [])
    MEMCPY = m.declare_func("memcpy", [I32, I32, I32], [])
    GETHDR = m.declare_func("get_header", [I32, I32, I32], [I32])
    APPVAL = m.declare_func("append_value", [], [])
    APPHDR = m.declare_func("append_header_or", [I32] * 5, [])
    SLOT = m.declare_func("slot", [I32, I32], [I32])
    ONREQ = m.declare_func("on_req", [I32], [])
    ONRESP = m.declare_func("on_resp", [I32], [])
    m.declare_func("proxy_on_memory_allocate", [I32], [I32])
    m.declare_func("proxy_on_request_headers", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_response_headers", [I32, I32, I32], [I32])
    m.declare_func("proxy_on_context_create", [I32, I32], [])
    m.declare_func("proxy_on_vm_start", [I32, I32], [I32])
    m.declare_func("proxy_on_configure", [I32, I32], [I32])
    m.declare_func("proxy_on_done", [I32], [I32])
    m.declare_func("proxy_on_delete", [I32], [])
    m.declare_func("proxy_on_log", [I32], [])
    m.declare_func("proxy_abi_version_0_2_0", [], [])

    def append_lit(a: Asm, text: str) -> None:
        ptr, length = S(text)
        a.i32_const(ptr).i32_const(length).call(APPEND)

    # -- alloc(size) -> ptr: bump, 8-aligned, wraps the arena ---------------
    a = Asm()
    a.global_get(G_BUMP).local_set(1)  # ptr = bump
    a.global_get(G_BUMP).local_get(0).i32_add().i32_const(7).i32_add()
    a.i32_const(-8).i32_and().global_set(G_BUMP)
    a.global_get(G_BUMP).i32_const(ARENA_HI).i32_gt_u().if_()
    a.i32_const(ARENA_LO).local_set(1)
    a.i32_const(ARENA_LO).local_get(0).i32_add().i32_const(7).i32_add()
    a.i32_const(-8).i32_and().global_set(G_BUMP)
    a.end()
    a.local_get(1)
    m.define_func("alloc", 1, a)

    # -- append(src, len): copy into the line buffer, clamped so oversized
    # headers can never run past the buffer into the context table ----------
    line_cap = CTX_TABLE - LINE_BUF
    a = Asm()
    # len = min(len, cap - line_len)
    a.local_get(1).i32_const(line_cap).global_get(G_LINE).i32_sub()
    a.i32_gt_u().if_()
    a.i32_const(line_cap).global_get(G_LINE).i32_sub().local_set(1)
    a.end()
    a.i32_const(0).local_set(2)
    a.block()
    a.loop()
    a.local_get(2).local_get(1).i32_ge_u().br_if(1)
    a.i32_const(LINE_BUF).global_get(G_LINE).i32_add().local_get(2).i32_add()
    a.local_get(0).local_get(2).i32_add().i32_load8_u()
    a.i32_store8()
    a.local_get(2).i32_const(1).i32_add().local_set(2)
    a.br(0)
    a.end()
    a.end()
    a.global_get(G_LINE).local_get(1).i32_add().global_set(G_LINE)
    m.define_func("append", 1, a)

    # -- memcpy(dst, src, len) ------------------------------------------------
    a = Asm()
    a.i32_const(0).local_set(3)
    a.block()
    a.loop()
    a.local_get(3).local_get(2).i32_ge_u().br_if(1)
    a.local_get(0).local_get(3).i32_add()
    a.local_get(1).local_get(3).i32_add().i32_load8_u()
    a.i32_store8()
    a.local_get(3).i32_const(1).i32_add().local_set(3)
    a.br(0)
    a.end()
    a.end()
    m.define_func("memcpy", 1, a)

    # -- get_header(map, kptr, klen) -> found; value at OUT_PTR/OUT_SIZE -----
    a = Asm()
    a.i32_const(OUT_PTR).i32_const(0).i32_store()
    a.i32_const(OUT_SIZE).i32_const(0).i32_store()
    a.local_get(0).local_get(1).local_get(2)
    a.i32_const(OUT_PTR).i32_const(OUT_SIZE).call(GET)
    a.if_(I32)  # nonzero status: not found / error
    a.i32_const(0)
    a.else_()
    a.i32_const(OUT_PTR).i32_load().i32_eqz().if_(I32)
    a.i32_const(0)
    a.else_()
    a.i32_const(OUT_SIZE).i32_load().i32_const(0).i32_gt_u()
    a.end()
    a.end()
    m.define_func("get_header", 0, a)

    # -- append_value(): append the header value the host wrote --------------
    a = Asm()
    a.i32_const(OUT_PTR).i32_load().i32_const(OUT_SIZE).i32_load().call(APPEND)
    m.define_func("append_value", 0, a)

    # -- append_header_or(map, kptr, klen, fbptr, fblen) ----------------------
    a = Asm()
    a.local_get(0).local_get(1).local_get(2).call(GETHDR)
    a.if_()
    a.call(APPVAL)
    a.else_()
    a.local_get(3).local_get(4).call(APPEND)
    a.end()
    m.define_func("append_header_or", 0, a)

    # -- slot(ctx, create) -> addr | 0 ---------------------------------------
    # Open addressing with TOMBSTONES (id -1): proxy_on_delete must not
    # zero slots in place or it would break the probe chains of colliding
    # live streams. Lookups probe past tombstones; creation reuses the
    # first tombstone seen once the key is proven absent.
    TOMB = -1
    a = Asm()
    # locals: 2=h, 3=tries, 4=addr, 5=id, 6=first_tombstone
    a.local_get(0).i32_const(-1640531527).i32_mul()
    a.i32_const(16).i32_shr_u().i32_const(CTX_SLOTS - 1).i32_and()
    a.local_set(2)
    a.i32_const(0).local_set(3)
    a.i32_const(0).local_set(6)
    a.block()
    a.loop()
    a.local_get(3).i32_const(CTX_SLOTS).i32_ge_u().br_if(1)  # probed all
    a.i32_const(CTX_TABLE).local_get(2).i32_const(CTX_SLOT_SIZE).i32_mul()
    a.i32_add().local_set(4)
    a.local_get(4).i32_load().local_set(5)
    a.local_get(5).local_get(0).i32_eq().if_()
    a.local_get(4).return_()
    a.end()
    a.local_get(5).i32_const(TOMB).i32_eq().if_()
    a.local_get(6).i32_eqz().if_()
    a.local_get(4).local_set(6)  # remember the first reusable slot
    a.end()
    a.else_()
    a.local_get(5).i32_eqz().if_()
    a.local_get(1).i32_eqz().if_()
    a.i32_const(0).return_()  # lookup miss
    a.end()
    a.local_get(6).if_()  # claim the earlier tombstone if any
    a.local_get(6).local_set(4)
    a.end()
    a.local_get(4).local_get(0).i32_store()
    a.local_get(4).i32_const(0).i32_store(4)
    a.local_get(4).return_()
    a.end()
    a.end()
    a.local_get(2).i32_const(1).i32_add().i32_const(CTX_SLOTS - 1).i32_and()
    a.local_set(2)
    a.local_get(3).i32_const(1).i32_add().local_set(3)
    a.br(0)
    a.end()
    a.end()
    # probed the whole table: claim a tombstone when creating
    a.local_get(1).if_()
    a.local_get(6).if_()
    a.local_get(6).local_get(0).i32_store()
    a.local_get(6).i32_const(0).i32_store(4)
    a.local_get(6).return_()
    a.end()
    a.end()
    a.i32_const(0)
    m.define_func("slot", 5, a)

    # -- on_req(ctx): build + log the [Request ...] line ----------------------
    no_id = S("NO_ID")
    a = Asm()
    # locals: 1=ids_start, 2=ids_len, 3=slot_addr
    a.i32_const(0).global_set(G_LINE)
    append_lit(a, "[Request ")
    a.global_get(G_LINE).local_set(1)
    for i, key in enumerate(
        ("x-request-id", "x-b3-traceid", "x-b3-spanid", "x-b3-parentspanid")
    ):
        kp, kl = S(key)
        a.i32_const(MAP_REQUEST).i32_const(kp).i32_const(kl)
        a.i32_const(no_id[0]).i32_const(no_id[1]).call(APPHDR)
        if i < 3:
            append_lit(a, "/")
    a.global_get(G_LINE).local_get(1).i32_sub().local_set(2)
    # remember the id block for the response/log phases
    a.local_get(0).i32_const(1).call(SLOT).local_set(3)
    a.local_get(3).if_()
    a.local_get(2).i32_const(IDS_CAP).i32_gt_u().if_()
    a.i32_const(IDS_CAP).local_set(2)
    a.end()
    a.local_get(3).local_get(2).i32_store(4)
    a.local_get(3).i32_const(8).i32_add()
    a.i32_const(LINE_BUF).local_get(1).i32_add()
    a.local_get(2).call(MEMCPY)
    a.end()
    append_lit(a, "] [")
    empty = S("")
    for key in (":method", None, ":authority", ":path"):
        if key is None:
            append_lit(a, " ")
            continue
        kp, kl = S(key)
        a.i32_const(MAP_REQUEST).i32_const(kp).i32_const(kl)
        a.i32_const(empty[0]).i32_const(empty[1]).call(APPHDR)
    append_lit(a, "]")
    ct = S("content-type")
    a.i32_const(MAP_REQUEST).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_()
    append_lit(a, " [ContentType ")
    a.call(APPVAL)
    append_lit(a, "]")
    a.end()
    a.i32_const(LOG_INFO).i32_const(LINE_BUF).global_get(G_LINE).call(LOG)
    a.drop()
    m.define_func("on_req", 3, a)

    # -- on_resp(ctx): the [Response ...] twin --------------------------------
    a = Asm()
    # locals: 1=slot_addr
    a.i32_const(0).global_set(G_LINE)
    append_lit(a, "[Response ")
    a.local_get(0).i32_const(0).call(SLOT).local_set(1)
    a.local_get(1).if_()
    a.local_get(1).i32_const(8).i32_add().local_get(1).i32_load(4).call(APPEND)
    a.else_()
    append_lit(a, "NO_ID/NO_ID/NO_ID/NO_ID")
    a.end()
    append_lit(a, "] [Status] ")
    st = S(":status")
    a.i32_const(MAP_RESPONSE).i32_const(st[0]).i32_const(st[1])
    a.i32_const(empty[0]).i32_const(empty[1]).call(APPHDR)
    ct = S("content-type")
    a.i32_const(MAP_RESPONSE).i32_const(ct[0]).i32_const(ct[1]).call(GETHDR)
    a.if_()
    append_lit(a, " [ContentType ")
    a.call(APPVAL)
    append_lit(a, "]")
    a.end()
    a.i32_const(LOG_INFO).i32_const(LINE_BUF).global_get(G_LINE).call(LOG)
    a.drop()
    m.define_func("on_resp", 1, a)

    # -- ABI surface ----------------------------------------------------------
    a = Asm()
    a.local_get(0).call(ALLOC)
    m.define_func("proxy_on_memory_allocate", 0, a)

    a = Asm()
    a.local_get(0).call(ONREQ)
    a.i32_const(0)  # Action::Continue
    m.define_func("proxy_on_request_headers", 0, a)

    a = Asm()
    a.local_get(0).call(ONRESP)
    a.i32_const(0)
    m.define_func("proxy_on_response_headers", 0, a)

    m.define_func("proxy_on_context_create", 0, Asm())

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_vm_start", 0, a)

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_configure", 0, a)

    a = Asm()
    a.i32_const(1)
    m.define_func("proxy_on_done", 0, a)

    a = Asm()
    a.local_get(0).i32_const(0).call(SLOT).local_tee(1).if_()
    a.local_get(1).i32_const(-1).i32_store()  # tombstone, not empty
    a.end()
    m.define_func("proxy_on_delete", 1, a)

    m.define_func("proxy_on_log", 0, Asm())
    m.define_func("proxy_abi_version_0_2_0", 0, Asm())

    for name in (
        "proxy_on_memory_allocate",
        "proxy_on_request_headers",
        "proxy_on_response_headers",
        "proxy_on_context_create",
        "proxy_on_vm_start",
        "proxy_on_configure",
        "proxy_on_done",
        "proxy_on_delete",
        "proxy_on_log",
        "proxy_abi_version_0_2_0",
    ):
        m.export_func(name)
    m.export_func("malloc", "alloc")  # legacy hosts allocate via malloc
    m.export_memory()

    base = min(off for off, _ in strings.values())
    end = max(off + ln for off, ln in strings.values())
    blob = bytearray(end - base)
    for text, (off, ln) in strings.items():
        blob[off - base : off - base + ln] = text.encode()
    m.add_data(base, bytes(blob))

    return m.build()


def main() -> None:
    out = Path(__file__).resolve().parent.parent / "envoy" / "filter" / "kmamiz_filter.wasm"
    binary = build()
    out.write_bytes(binary)
    print(f"wrote {out} ({len(binary)} bytes)")


if __name__ == "__main__":
    main()
