"""Extract pure-literal test fixtures from the reference's TS mock data.

Reads /root/reference/tests/MockData.ts and MockData2.ts, slices selected
`const X = [...]` blocks, converts the JS object literals to JSON, and
writes tests/fixtures/*.json. This extracts captured DATA (real Zipkin
traces from Istio Bookinfo and PDAS, envoy log lines) to serve as the
cross-implementation parity corpus — no reference code is copied.

Usage: python tools/extract_fixtures.py
"""
from __future__ import annotations

import json
import re
from pathlib import Path

REF = Path("/root/reference/tests")
OUT = Path(__file__).resolve().parent.parent / "tests" / "fixtures"

_UNDEF = "_UNDEFINED_"


def slice_const(source: str, name: str) -> str:
    """Return the JS expression assigned to `const <name> =` (brace-matched)."""
    m = re.search(rf"^const {re.escape(name)}[^=]*=", source, re.M)
    if not m:
        raise KeyError(name)
    i = m.end()
    # find the start bracket
    while source[i] in " \n\t":
        i += 1
    start = i
    depth = 0
    in_str: str | None = None
    while i < len(source):
        c = source[i]
        if in_str:
            if c == "\\":
                i += 2
                continue
            if c == in_str:
                in_str = None
        elif c in "\"'`":
            in_str = c
        elif c in "[{(":
            depth += 1
        elif c in "]})":
            depth -= 1
            if depth == 0:
                return source[start : i + 1]
        elif c == "/" and source[i : i + 2] == "//":
            i = source.index("\n", i)
        i += 1
    raise ValueError(f"unbalanced block for {name}")


def strip_comments(js: str) -> str:
    out = []
    i = 0
    in_str: str | None = None
    while i < len(js):
        c = js[i]
        if in_str:
            if c == "\\":
                out.append(js[i : i + 2])
                i += 2
                continue
            if c == in_str:
                in_str = None
            out.append(c)
        elif c in "\"'`":
            in_str = c
            out.append(c)
        elif c == "/" and js[i : i + 2] == "//":
            i = js.index("\n", i)
            continue
        else:
            out.append(c)
        i += 1
    return "".join(out)


def js_to_json(js: str) -> str:
    """Convert a comment-free JS literal to JSON text (string-aware scan)."""
    out = []
    i = 0
    n = len(js)
    while i < n:
        c = js[i]
        if c in "\"'":
            quote = c
            buf = []
            i += 1
            while i < n:
                ch = js[i]
                if ch == "\\":
                    nxt = js[i + 1]
                    if nxt == "'":
                        buf.append("'")
                    else:
                        buf.append(ch + nxt)
                    i += 2
                    continue
                if ch == quote:
                    break
                if ch == '"' and quote == "'":
                    buf.append('\\"')
                elif ch == "\n":
                    buf.append("\\n")
                elif ch == "\t":
                    buf.append("\\t")
                else:
                    buf.append(ch)
                i += 1
            out.append('"' + "".join(buf) + '"')
            i += 1
            continue
        out.append(c)
        i += 1
    text = "".join(out)
    # unquoted identifier keys -> quoted
    text = re.sub(r"([{,\[]\s*)([A-Za-z_$][\w$]*)\s*:", r'\1"\2":', text)
    # undefined values -> sentinel
    text = re.sub(r":\s*undefined", f': "{_UNDEF}"', text)
    # trailing commas
    text = re.sub(r",(\s*[}\]])", r"\1", text)
    return text


def drop_undefined(obj):
    if isinstance(obj, list):
        return [drop_undefined(o) for o in obj]
    if isinstance(obj, dict):
        return {k: drop_undefined(v) for k, v in obj.items() if v != _UNDEF}
    return obj


def extract(source: str, name: str):
    return drop_undefined(json.loads(js_to_json(strip_comments(slice_const(source, name)))))


def extract_template_lines(source: str, name: str):
    """Extract a backtick template string split('\\n') into a list of lines."""
    block = slice_const(source, name)
    m = re.search(r"`(.*)`", block, re.S)
    assert m, name
    return m.group(1).split("\n")


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    mock1 = (REF / "MockData.ts").read_text()
    mock2 = (REF / "MockData2.ts").read_text()

    fixtures = {
        "bookinfo_traces": extract(mock1, "MockTrace"),
        "bookinfo_endpoint_dependencies": extract(mock1, "MockEndpointDependencies"),
        "pdas_traces": extract(mock1, "MockTracePDAS"),
        "pdas_realtime_data": extract(mock1, "MockRlDataPDAS"),
        "pdas_endpoint_dependencies": extract(mock1, "MockEndpointDependenciesPDAS"),
        "pdas_endpoint_info_1": extract(mock1, "MockEndpointInfoPDAS1"),
        "pdas_envoy_log_lines": extract_template_lines(mock1, "MockLogsPDAS"),
        "pdas2_traces": extract(mock2, "traces"),
        "pdas2_raw_logs": extract(mock2, "rawLogs"),
    }
    for fname, data in fixtures.items():
        path = OUT / f"{fname}.json"
        path.write_text(json.dumps(data, indent=1, ensure_ascii=False))
        kind = f"{len(data)} items" if isinstance(data, list) else "object"
        print(f"wrote {path.name}: {kind}")


if __name__ == "__main__":
    main()
