"""Benchmark: span-window ingest throughput + graph-metric refresh latency.

Run on real TPU hardware by the driver. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

The HEADLINE metric is the deployed big-window ingest path: paginated raw
Zipkin JSON chunks through DataProcessor.ingest_raw_stream — native SoA
parse of chunk k+1 (native/kmamiz_spans.cpp, GIL released) overlapping
chunk k's intern/pack + device window-merge into the persistent endpoint
graph — exactly the route POST /ingest and the first-time-setup backfill
run in production (server/processor.py, server/dp_server.py). The one
phase NOT charged is the host->device copy, which in this dev harness
rides a ~10 MB/s tunnel (PCIe on a real TPU VM): the stream path measures
it per chunk and the headline reconstructs the pipeline's critical path
with the copy excluded (see critical_path_ms); the measured tunnel-
inclusive wall is reported alongside. The serial one-shot path, the
device-only chain, and the 2,500-trace DP tick are extras.

Workload (BASELINE.json configs): a MicroViSim-scale synthetic mesh with
1k services / 10k endpoints and a 1M-span window — the reference caps at
2,500 traces per 5 s tick (~<20k spans/sec sustained; see BASELINE.md), and
the north-star target is >=1M spans/sec with p50 full risk+instability graph
refresh < 50 ms at 10k endpoints.

Noise method (VERDICT r3 #1): this host's wall-clock noise is large and
strictly ADDITIVE (scheduler preemption, memory pressure: the same parse
measures 1.0 s quiet and 5+ s under load — never faster than the machine's
capability). Throughput metrics therefore report BEST-of-N as the headline
estimator with the full rep list and median in the extras, so one loaded
rep can no longer sink the number of record; latency metrics (graph
refresh, HTTP p50) keep the median, since "typical" is what a latency SLA
is about. Each estimator is labeled in the extras.

Timing method (important on this setup): the TPU is reached through a
tunnel where jax.block_until_ready can return before the device work has
actually run, and a device round trip costs ~100 ms. Each device-chain
measurement therefore chains ITERS kernel invocations inside ONE jitted
lax.fori_loop with a loop-carried data dependence (so nothing can be
hoisted or elided), fetches a single scalar digest of every output to the
host (which genuinely drains the queue), and reports
(total - tunnel_rtt) / ITERS. The rtt baseline is measured the same way
on a trivial kernel and reported alongside.
"""
from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

N_SPANS = 1 << 20  # ~1M spans per window
N_ENDPOINTS = 10_000
N_SERVICES = 1_000
N_STATUSES = 8
SPANS_PER_TRACE = 7
GRAPH_EDGES = 50_000
BASELINE_SPANS_PER_SEC = 1_000_000.0  # BASELINE.json north star
ITERS = 8


def _reps(run, reps: int = 5):
    """Wall times of `reps` runs of run() (which must block on real
    results), after one unrecorded warmup/compile run."""
    run()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return times


def _timed(run, reps: int = 5):
    """BEST-of-reps wall time: on this box noise is strictly additive, so
    the minimum is the honest estimator of machine capability (VERDICT r3
    #1). Callers that want "typical" latency use _timed_median."""
    return float(min(_reps(run, reps)))


def _timed_median(run, reps: int = 5):
    """median-of-reps wall time: the right estimator for latency metrics
    where a typical run, not peak capability, is the claim."""
    return float(np.median(_reps(run, reps)))


# the bench's synthetic raw-Zipkin windows come from the shared generator
# (legacy 200-svc/50-url defaults reproduce the historical bench shape
# byte for byte; urls_per_service>0 selects the BASELINE 10k-endpoint
# shape). Re-exported so tools/profile_parse.py keeps profiling the exact
# workload the headline measures.
from kmamiz_tpu.synth import make_raw_window  # noqa: E402


def critical_path_ms(chunk_detail, drain_ms: float) -> float:
    """Reconstruct the streaming pipeline's wall time with the
    host->device copy priced at zero, composing MEASURED per-chunk phase
    times on the pipeline's actual dataflow (server/processor.py
    ingest_raw_stream):

      worker thread: parse(0), parse(1), ... (parse k+1 is submitted
        right after the main loop receives chunk k)
      main thread:   receive k -> pack+dispatch k (merge_ms minus the
        measured transfer_ms) -> wait for parse k+1
      tail:          drain_ms (the final device sync on n_edges)

    This charges every framework phase — parse, intern, pack, dispatch,
    device drain — and excludes ONLY the measured copy time, the same
    exclusion policy the serial headline has used since round 1 (the copy
    rides a ~10 MB/s dev-harness tunnel; on a TPU VM it is PCIe at GB/s).
    """
    if not chunk_detail:
        return float(drain_ms)
    t_main = chunk_detail[0]["parse_ms"]
    for i, d in enumerate(chunk_detail):
        submit_next = t_main
        t_main += max(d["merge_ms"] - d["transfer_ms"], 0.0)
        if i + 1 < len(chunk_detail):
            t_main = max(t_main, submit_next + chunk_detail[i + 1]["parse_ms"])
    return t_main + drain_ms


def main() -> None:
    global BENCH_T0
    BENCH_T0 = time.perf_counter()
    # persistent XLA compilation cache, repo-local by default: the
    # deployment ships with KMAMIZ_COMPILE_CACHE_DIR wired (deploy/
    # kmamiz-tpu.yaml), so the bench measures the deployed
    # configuration — steady-state programs load from disk instead of
    # paying 50-70 s union compiles every run. Cold-compile behavior
    # stays measured: the warm-boot subsection runs subprocesses against
    # its OWN empty/warm cache dirs, and a fresh checkout's first bench
    # run still records the cold walls. Opt out (fully cold run) with
    # KMAMIZ_BENCH_NO_COMPILE_CACHE=1.
    if os.environ.get("KMAMIZ_BENCH_NO_COMPILE_CACHE") != "1":
        os.environ.setdefault(
            "KMAMIZ_COMPILE_CACHE_DIR",
            str(Path(__file__).resolve().parent / ".xla-cache"),
        )
        from kmamiz_tpu.core import compile_cache

        compile_cache.enable_from_env()
    import jax
    import jax.numpy as jnp

    from kmamiz_tpu.core.spans import pack_trace_rows
    from kmamiz_tpu.ops import scorers, window

    rng = np.random.default_rng(0)

    # ---- tunnel round-trip baseline ---------------------------------------
    @jax.jit
    def _trivial(x):
        return jnp.sum(x)

    small = jnp.ones(8, jnp.float32)
    rtt = _timed(lambda: float(_trivial(small)))

    # ---- window pipeline inputs: 1M-span synthetic window ------------------
    endpoint_id = rng.integers(0, N_ENDPOINTS, N_SPANS, dtype=np.int32)
    status_id = jnp.asarray(rng.integers(0, N_STATUSES, N_SPANS, dtype=np.int32))
    status_class = jnp.asarray(
        rng.choice([2, 4, 5], N_SPANS, p=[0.95, 0.04, 0.01]).astype(np.int8)
    )
    latency = jnp.asarray(rng.gamma(2.0, 50.0, N_SPANS).astype(np.float32))
    ts_rel = jnp.asarray(rng.integers(0, 30_000_000, N_SPANS, dtype=np.int32))
    valid = jnp.ones(N_SPANS, dtype=bool)

    # forest of ~7-span traces, alternating CLIENT/SERVER, trace-row packed
    # for the MXU ancestor walk (the production merge path layout)
    trace_of = (np.arange(N_SPANS) // SPANS_PER_TRACE).astype(np.int32)
    parent = np.arange(-1, N_SPANS - 1, dtype=np.int32)
    parent[::SPANS_PER_TRACE] = -1
    kind = np.full(N_SPANS, 1, dtype=np.int8)
    kind[1::2] = 2

    def host_pack():
        packed = pack_trace_rows(trace_of, N_SPANS, parent)
        return packed, packed.parent_slots(parent)

    packing_host_ms = _timed(lambda: host_pack(), reps=3) * 1000
    packed, pslot = host_pack()

    from kmamiz_tpu.core.spans import _pad_size as _pow2

    bench_depth = min(
        window.MAX_DEPTH, _pow2(max(1, packed.max_trace_len - 1), minimum=4)
    )
    parent_slot2 = jnp.asarray(packed.pack(pslot, -1))
    kind2 = jnp.asarray(packed.pack(kind, 0))
    valid2 = jnp.asarray(packed.pack(np.ones(N_SPANS, bool), False))
    ep2 = jnp.asarray(packed.pack(endpoint_id, 0))
    endpoint_id = jnp.asarray(endpoint_id)

    def digest(parts):
        return sum(jnp.sum(p.astype(jnp.float32)) for p in parts)

    @jax.jit
    def window_chain():
        def body(_i, acc):
            # loop-carried dependence: no iteration can be hoisted/elided
            stats = window.window_stats(
                endpoint_id,
                status_id,
                status_class,
                latency + acc * 1e-12,
                ts_rel,
                valid,
                num_endpoints=N_ENDPOINTS,
                num_statuses=N_STATUSES,
            )
            # production merge policy: walk depth capped to the window's
            # longest chain, pow2-bucketed (graph/store.py merge_window)
            edges = window.dependency_edges_packed(
                parent_slot2,
                kind2,
                valid2,
                ep2 + (acc > 1e30).astype(jnp.int32),
                max_depth=bench_depth,
            )
            return acc + digest(tuple(stats)) + digest(tuple(edges))

        return jax.lax.fori_loop(0, ITERS, body, 0.0)

    # ---- custom-kernel story: MXU packed walk vs flat gather walk ----------
    # the trace-row-packed ancestor walk (one-hot einsums on the MXU) is the
    # production default; the flat gather walk is what a naive translation
    # would do. Chained+rtt-adjusted like everything else, few reps (the
    # gather walk is ~1.1 s/iter).
    WALK_MXU_ITERS, WALK_FLAT_ITERS = 8, 2  # flat is ~2 orders slower

    @jax.jit
    def flat_walk_chain():
        def body(_i, acc):
            # like-for-like: SAME depth cap as the packed walk
            edges = window.dependency_edges(
                jnp.asarray(parent),
                jnp.asarray(kind),
                jnp.ones(N_SPANS, bool),
                endpoint_id + (acc > 1e30).astype(jnp.int32),
                max_depth=bench_depth,
            )
            return acc + digest(tuple(edges))

        return jax.lax.fori_loop(0, WALK_FLAT_ITERS, body, 0.0)

    @jax.jit
    def mxu_walk_chain():
        def body(_i, acc):
            edges = window.dependency_edges_packed(
                parent_slot2,
                kind2,
                valid2,
                ep2 + (acc > 1e30).astype(jnp.int32),
                max_depth=bench_depth,
            )
            return acc + digest(tuple(edges))

        return jax.lax.fori_loop(0, WALK_MXU_ITERS, body, 0.0)

    walk_mxu_ms = (
        max(_timed(lambda: float(mxu_walk_chain()), reps=3) - rtt, 0)
        / WALK_MXU_ITERS
        * 1000
    )
    walk_flat_ms = (
        max(_timed(lambda: float(flat_walk_chain()), reps=3) - rtt, 0)
        / WALK_FLAT_ITERS
        * 1000
    )

    # the SHARDED packed walk (parallel/mesh.py) on a 1-device TPU mesh:
    # same kernel the multi-chip dryrun runs on 8 virtual devices — this
    # records the real-TPU per-chip cost of the sharded code path
    from kmamiz_tpu.parallel import mesh as pmesh

    mesh1 = pmesh.make_mesh(1)

    @jax.jit
    def sharded_walk_chain():
        # single iteration: the flat sharded walk is ~600 ms/iter, one is
        # plenty and needs no anti-hoisting ceremony
        _a, _d, _ds, m = pmesh.sharded_dependency_edges(
            mesh1,
            jnp.asarray(parent),
            jnp.asarray(kind),
            jnp.ones(N_SPANS, bool),
            endpoint_id,
            max_depth=bench_depth,
        )
        return jnp.sum(m.astype(jnp.float32))

    @jax.jit
    def sharded_packed_walk_chain():
        def body(_i, acc):
            _a, _d, _ds, m = pmesh.sharded_dependency_edges_packed(
                mesh1,
                parent_slot2,
                kind2,
                valid2,
                ep2 + (acc > 1e30).astype(jnp.int32),
                max_depth=bench_depth,
            )
            return acc + jnp.sum(m.astype(jnp.float32))

        return jax.lax.fori_loop(0, WALK_MXU_ITERS, body, 0.0)

    walk_sharded_packed_ms = (
        max(_timed(lambda: float(sharded_packed_walk_chain()), reps=3) - rtt, 0)
        / WALK_MXU_ITERS
        * 1000
    )
    walk_sharded_flat_ms = (
        max(_timed(lambda: float(sharded_walk_chain()), reps=3) - rtt, 0) * 1000
    )

    total = _timed(lambda: float(window_chain()))
    # sustained ingest charges the per-window host packing cost the
    # production merge path pays, not just the device chain
    ingest_dt = max(total - rtt, 1e-9) / ITERS + packing_host_ms / 1000
    spans_per_sec = N_SPANS / ingest_dt

    # ---- HONEST end-to-end ingest: raw Zipkin bytes -> window stats --------
    # The device-chain number above excludes the host-side conversion of raw
    # Zipkin JSON. This metric charges the WHOLE path on every rep: native
    # JSON scan (native/kmamiz_spans.cpp) -> SoA batch + interning ->
    # host->device transfer -> window stats + MXU dependency walk -> result
    # fetch. Span shape mirrors an Istio sidecar span (istio tags, status,
    # url; make_raw_window at module level, shared with
    # tools/profile_parse.py so parse profiles stay comparable to the
    # headline); bytes/span is reported alongside.
    from kmamiz_tpu.core.spans import raw_spans_to_batch

    E2E_TRACES = 150_000  # x7 spans = 1.05M spans per window
    # BASELINE workload shape (VERDICT r4 #3): the full 1k-service /
    # 10k-endpoint MicroViSim-scale mesh with >=100k distinct edges, so
    # interning, shape tables, and the union sort carry production
    # cardinality (the legacy 200-svc/50-url shape rides along as a
    # continuity extra below)
    URLS_PER_SVC = N_ENDPOINTS // N_SERVICES
    raw_window = make_raw_window(
        E2E_TRACES,
        SPANS_PER_TRACE,
        n_services=N_SERVICES,
        urls_per_service=URLS_PER_SVC,
    )
    e2e_n_spans = E2E_TRACES * SPANS_PER_TRACE
    e2e_bytes_per_span = len(raw_window) / e2e_n_spans

    # segment counts are a jit-static shape: learn them from one probe parse
    # (fresh interner per rep -> identical counts every rep)
    _probe = raw_spans_to_batch(raw_window)
    E2E_NUM_ENDPOINTS = _probe[0].num_endpoints if _probe else 1
    E2E_NUM_STATUSES = _probe[0].num_statuses if _probe else 1
    del _probe

    @jax.jit
    def e2e_device(eid, sid, scl, lat, ts, val, pslot2, kind2, valid2, ep2):
        stats = window.window_stats(
            eid,
            sid,
            scl,
            lat,
            ts,
            val,
            num_endpoints=E2E_NUM_ENDPOINTS,
            num_statuses=E2E_NUM_STATUSES,
        )
        edges = window.dependency_edges_packed(
            pslot2, kind2, valid2, ep2, max_depth=8
        )
        return digest(tuple(stats)) + digest(tuple(edges))

    def raw_e2e_once():
        """One full ingest, phase-timed: returns (parse_s, pack_s,
        transfer_s, device_s) or None when the native loader is absent."""
        t0 = time.perf_counter()
        out = raw_spans_to_batch(raw_window)
        if out is None:
            return None
        batch, _kept = out
        t1 = time.perf_counter()
        packed = pack_trace_rows(
            batch.trace_of, batch.n_spans, batch.parent_idx
        )
        pslot = packed.parent_slots(batch.parent_idx)
        host_arrays = [
            batch.endpoint_id,
            batch.status_id,
            batch.status_class,
            batch.latency_ms.astype(np.float32),
            batch.timestamp_rel,
            batch.valid,
            packed.pack(pslot, -1),
            packed.pack(batch.kind[: batch.n_spans], 0),
            packed.pack(np.ones(batch.n_spans, bool), False),
            packed.pack(batch.endpoint_id[: batch.n_spans], 0),
        ]
        t2 = time.perf_counter()
        dev_arrays = jax.block_until_ready(
            [jnp.asarray(a) for a in host_arrays]
        )
        t3 = time.perf_counter()
        float(e2e_device(*dev_arrays))  # compute + scalar fetch
        t4 = time.perf_counter()
        return (t1 - t0, t2 - t1, t3 - t2, t4 - t3)

    e2e_phases = None
    e2e_work_reps_ms = []
    if raw_e2e_once() is not None:  # warms the compile
        # 5 reps, BEST rep's phases (min framework-work time): noise on
        # this box is strictly additive, so the minimum is the honest
        # estimator of machine capability (VERDICT r3 #1); the full rep
        # list is reported so the spread is visible
        reps = [raw_e2e_once() for _ in range(5)]
        works = [(r[0] + r[1] + r[3], r) for r in reps]
        e2e_work_reps_ms = [round(w * 1000, 1) for w, _ in works]
        e2e_phases = min(works, key=lambda x: x[0])[1]

    # ---- native parse thread scaling (honest: this host has 1 core) --------
    # the parallel scan (prescan + worker ranges + atomic id table) is built
    # for the multi-core DP deployment; on this single-core dev box extra
    # threads just timeslice, so walls are reported per thread count with
    # the phase breakdown rather than claiming a speedup. Best-of-2 per
    # thread count, same additive-noise rationale as the headline.
    from kmamiz_tpu import native as native_mod

    parse_scaling = {}
    if e2e_phases is not None:
        for T in (1, 2, 4):
            best = None
            for _ in range(2):
                t0 = time.perf_counter()
                out = native_mod.parse_spans(raw_window, threads=T)
                wall = time.perf_counter() - t0
                if out is None:
                    break
                if best is None or wall < best[0]:
                    best = (wall, out["timings"])
            if best is None:
                break
            wall, tm = best
            parse_scaling[f"t{T}"] = {
                "wall_ms": round(wall * 1000, 1),
                "prescan_ms": round(tm["prescan_us"] / 1000, 1),
                "parse_busy_max_ms": round(tm["parse_us"] / 1000, 1),
                "merge_ms": round(tm["merge_us"] / 1000, 1),
            }

    # ---- columnar wire format (KMZC) on the identical window ---------------
    # production pays the encode on the filter side (envoy/filter/main.go,
    # amortized across sidecars); the server-side cost is ONLY the decode,
    # so the frame is built once uncounted and the native decoder is timed
    # against the JSON scan of the same spans (docs/INGEST_WIRE.md).
    # Best-of-3, same additive-noise rationale as every throughput number.
    wire_extras = {}
    if e2e_phases is not None and native_mod.supports_columnar():
        from kmamiz_tpu.core import wire as wire_mod

        kmzc_frame = wire_mod.encode_groups(json.loads(raw_window))
        col_best = None
        for _ in range(3):
            t0 = time.perf_counter()
            out = native_mod.parse_spans(kmzc_frame)
            wall = time.perf_counter() - t0
            if out is None:
                break
            if col_best is None or wall < col_best:
                col_best = wall
        if col_best is not None:
            json_parse_s, pack_s, _, device_s = e2e_phases
            col_work_s = col_best + pack_s + device_s
            wire_extras = {
                "e2e_wire_json_bytes": len(raw_window),
                "e2e_wire_columnar_bytes": len(kmzc_frame),
                "e2e_wire_bytes_ratio": round(
                    len(raw_window) / len(kmzc_frame), 2
                ),
                "e2e_columnar_parse_ms": round(col_best * 1000, 1),
                "e2e_columnar_parse_speedup_vs_json": round(
                    json_parse_s / col_best, 2
                ),
                # serial-path rate with the columnar decode substituted
                # for the JSON scan (pack + device phases unchanged)
                "e2e_columnar_serial_spans_per_sec": round(
                    e2e_n_spans / col_work_s, 0
                ),
            }
        del kmzc_frame

    # ---- THE HEADLINE: deployed pipelined streaming ingest -----------------
    # DataProcessor.ingest_raw_stream over paginated raw chunks — the
    # exact production route (POST /ingest, first-time-setup backfill):
    # native parse of chunk k+1 on the worker thread overlaps chunk k's
    # pack + transfer + device merge into the persistent endpoint graph.
    # Chunks model paginated Zipkin fetches; same total span population
    # as the serial e2e. Counted reps feed ONE persistent processor fresh
    # windows (distinct trace ids, identical naming shapes) — the
    # steady-state production mix; the cold first window (boot interning
    # + compile walls) and the r4-style fresh-processor legacy shape are
    # reported alongside. The measured wall INCLUDES the tunnel copy;
    # the headline excludes it via critical_path_ms over per-chunk
    # measured phases.
    from kmamiz_tpu.server.processor import (
        DEFAULT_STREAM_CHUNKS,
        DataProcessor,
    )

    N_CHUNKS = DEFAULT_STREAM_CHUNKS
    chunk_traces = E2E_TRACES // N_CHUNKS

    def make_stream_chunks(prefix: str, baseline: bool = True):
        kw = (
            dict(n_services=N_SERVICES, urls_per_service=URLS_PER_SVC)
            if baseline
            else {}
        )
        return [
            make_raw_window(
                chunk_traces,
                SPANS_PER_TRACE,
                t_start=i * chunk_traces,
                trace_prefix=prefix,
                **kw,
            )
            for i in range(N_CHUNKS)
        ]

    def stream_once(dp, chunks):
        t0 = time.perf_counter()
        try:
            summary = dp.ingest_raw_stream(iter(chunks))
        except ValueError:
            return None
        return time.perf_counter() - t0, summary

    # STEADY-STATE methodology: production serves windows from a
    # PERSISTENT processor — XLA programs compiled, naming shapes
    # interned at boot, every window deduping as new traces. Each rep
    # feeds the same processor a fresh window with distinct trace ids
    # but identical naming shapes (trace_prefix), exactly the
    # steady-state mix; the cold first window (boot interning + compile
    # walls included) is reported alongside, as is the r4-style
    # legacy-shape fresh-processor run for continuity.
    stream_walls_ms = []
    stream_cp_ms = []
    stream_best = None
    stream_cold_extras = {}
    stream_legacy_extras = {}
    stream_upload_extras = {}
    if e2e_phases is not None:
        # virtual clock: advancing past the 5-min dedup TTL between reps
        # keeps the processed-trace map at its production steady size
        # (~one window of ids) instead of accumulating every rep's ids —
        # the skip-set cost each parse pays stays the steady-state one
        bench_clock = {"ms": 1_700_000_000_000.0}
        dp_stream = DataProcessor(
            trace_source=lambda lb, t, lim: [],
            now_ms=lambda: bench_clock["ms"],
        )
        cold = stream_once(dp_stream, make_stream_chunks("c"))
        if cold is not None:
            cold_wall_s, cold_summary = cold
            stream_cold_extras = {
                "e2e_stream_cold_wall_ms": round(cold_wall_s * 1000, 1),
                "e2e_stream_cold_cp_ms": round(
                    critical_path_ms(
                        cold_summary["chunk_detail"],
                        cold_summary["drain_ms"],
                    ),
                    1,
                ),
            }
            # one uncounted steady rep absorbs the steady-shape union
            # compile: the cold window's drain unions run at the initial
            # store capacities, steady windows at the grown one — a
            # different program that would otherwise bill its compile
            # wall to the first counted rep
            bench_clock["ms"] += 301_000  # TTL-prune the cold window's ids
            stream_once(dp_stream, make_stream_chunks("s"))
            # 6 counted reps: the shared 1-core host's load spikes sink
            # individual reps by 30%+; with additive noise the BEST rep
            # estimates machine capability and more draws tighten it
            # (full rep list reported)
            for k in range(6):
                bench_clock["ms"] += 301_000
                chunks = make_stream_chunks(f"r{k}x")
                out = stream_once(dp_stream, chunks)
                del chunks
                if out is None:
                    continue
                wall_s, summary = out
                cp = critical_path_ms(
                    summary["chunk_detail"], summary["drain_ms"]
                )
                stream_walls_ms.append(round(wall_s * 1000, 1))
                stream_cp_ms.append(round(cp, 1))
                if stream_best is None or cp < stream_best[0]:
                    stream_best = (cp, wall_s, summary)

            # double-buffered upload pipeline counters over the whole
            # steady run: blocked_ms is the wall the host ACTUALLY spent
            # waiting on transfers (the legacy synchronous path charged
            # the full copy time here — BENCH_r03's 3895 ms dead time)
            up = dp_stream.graph.upload_stats()
            stream_upload_extras = {
                "e2e_upload_depth": up["depth"],
                "e2e_upload_count": up["uploads"],
                "e2e_upload_peak_in_flight": up["peak_in_flight"],
                "e2e_upload_blocked_ms": round(up["blocked_ms"], 1),
            }

            # legacy-shape continuity (the r3/r4 headline methodology:
            # fresh processor + graph every rep, 200-svc/50-url window)
            legacy_chunks = make_stream_chunks("w", baseline=False)

            def legacy_once():
                dp = DataProcessor(trace_source=lambda lb, t, lim: [])
                return stream_once(dp, legacy_chunks)

            if legacy_once() is not None:  # warm legacy-shape programs
                legacy_best = None
                legacy_walls = []
                for _ in range(3):
                    out = legacy_once()
                    if out is None:
                        continue
                    wall_s, summary = out
                    cp = critical_path_ms(
                        summary["chunk_detail"], summary["drain_ms"]
                    )
                    legacy_walls.append(round(wall_s * 1000, 1))
                    if legacy_best is None or cp < legacy_best[0]:
                        legacy_best = (cp, summary)
                if legacy_best is not None:
                    lcp, lsummary = legacy_best
                    stream_legacy_extras = {
                        "e2e_stream_legacy_spans_per_sec": round(
                            lsummary["spans"] / (lcp / 1000.0), 0
                        ),
                        "e2e_stream_legacy_cp_ms": round(lcp, 1),
                        "e2e_stream_legacy_wall_reps_ms": legacy_walls,
                        "e2e_stream_legacy_endpoints": lsummary["endpoints"],
                        "e2e_stream_legacy_edges": lsummary["edges"],
                    }
            del legacy_chunks

    # ---- graftstream micro-tick freshness (ISSUE 16) -----------------------
    # The overlapped micro-tick engine (server/stream.py) vs the serial
    # collect tick over the scenario factory's burst + diurnal traffic
    # curves: per-curve span-arrival -> forecast-visible p99 from the
    # telemetry freshness plane (worst curve is the gated headline,
    # absolute ceiling 250 ms in tools/slo_report.py), the stream/serial
    # wall ratio, and the steady-state recompile count after a serial
    # warm epoch (must be zero — the keys are always present, None only
    # when the whole section fails).
    stream_tick_extras = {
        "stream_freshness_ms_p99": None,
        "stream_vs_batch_speedup": None,
        "stream_steady_recompiles": None,
        "stream_zero_recompiles_pass": None,
    }
    try:
        import random as _stream_rand

        from kmamiz_tpu.core import programs as _programs
        from kmamiz_tpu.scenarios.traffic import sample_traffic
        from kmamiz_tpu.server.stream import StreamEngine
        from kmamiz_tpu.telemetry import freshness as tel_freshness

        STREAM_TICKS = 24
        STREAM_SPANS_PER_TRACE = 5

        _stream_feed: list = []

        def _stream_src(lb, t, lim):
            # only the engine's single producer thread pops, in order
            return _stream_feed.pop(0) if _stream_feed else []

        dp_tick = DataProcessor(
            trace_source=_stream_src, use_device_stats=False
        )

        def _tick_windows(curve, prefix):
            return [
                json.loads(
                    make_raw_window(
                        int(n),
                        STREAM_SPANS_PER_TRACE,
                        t_start=i * 1_000,
                        trace_prefix=f"{prefix}{i}",
                    )
                )
                for i, n in enumerate(curve)
            ]

        def _tick_requests(prefix, count, t_base):
            return [
                {
                    "uniqueId": f"{prefix}{i}",
                    "lookBack": 30_000,
                    "time": t_base + i,
                }
                for i in range(count)
            ]

        def _run_serial(windows, prefix):
            _stream_feed.extend(windows)
            reqs = _tick_requests(prefix, len(windows), 1_000_000)
            t0 = time.perf_counter()
            for req in reqs:
                dp_tick.collect(req)
            return time.perf_counter() - t0

        def _run_stream(windows, prefix):
            _stream_feed.extend(windows)
            reqs = _tick_requests(prefix, len(windows), 2_000_000)
            eng = StreamEngine(dp_tick)
            t0 = time.perf_counter()
            eng.run_stream(reqs)
            return time.perf_counter() - t0

        stream_curves = {
            "burst": sample_traffic(
                "burst", STREAM_TICKS, _stream_rand.Random(7)
            ),
            "diurnal": sample_traffic(
                "diurnal", STREAM_TICKS, _stream_rand.Random(11)
            ),
        }
        # warm epoch: every window shape of both curves through the
        # serial parity path, so the measured runs below are steady
        # state for BOTH engines (same programs, same bucket shapes)
        for cname, curve in stream_curves.items():
            _run_serial(_tick_windows(curve, f"mtw-{cname}-"), f"mtw-{cname}-")
        stream_prog_snap = _programs.snapshot()

        stream_fresh_p99 = {}
        stream_speedup = {}
        for cname, curve in stream_curves.items():
            serial_s = _run_serial(
                _tick_windows(curve, f"mts-{cname}-"), f"mts-{cname}-"
            )
            tel_freshness.reset_for_tests()
            stream_s = _run_stream(
                _tick_windows(curve, f"mtp-{cname}-"), f"mtp-{cname}-"
            )
            fr = tel_freshness.snapshot()
            stream_fresh_p99[cname] = fr["freshness_ms_p99"]
            stream_speedup[cname] = serial_s / max(stream_s, 1e-9)
        stream_new_compiles = {
            k: v
            for k, v in _programs.new_compiles_since(stream_prog_snap).items()
            if v
        }
        stream_tick_extras = {
            # worst curve is the gate: the SLO holds under both shapes
            "stream_freshness_ms_p99": round(
                max(stream_fresh_p99.values()), 2
            ),
            "stream_freshness_by_curve_ms_p99": {
                k: round(v, 2) for k, v in stream_fresh_p99.items()
            },
            "stream_vs_batch_speedup": round(
                min(stream_speedup.values()), 3
            ),
            "stream_steady_recompiles": sum(stream_new_compiles.values()),
            "stream_zero_recompiles_pass": not stream_new_compiles,
            "stream_ticks_per_curve": STREAM_TICKS,
        }
        del dp_tick
    except Exception as e:  # noqa: BLE001 - keys stay present (None)
        print(f"stream micro-tick section failed: {e!r}", file=sys.stderr)

    # ---- graph metric refresh @10k endpoints -------------------------------
    ep_service = jnp.asarray(
        rng.integers(0, N_SERVICES, N_ENDPOINTS, dtype=np.int32)
    )
    ep_ml = jnp.asarray(rng.integers(0, 4096, N_ENDPOINTS, dtype=np.int32))
    ep_record = jnp.ones(N_ENDPOINTS, dtype=bool)
    src = jnp.asarray(rng.integers(0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32))
    dst = jnp.asarray(rng.integers(0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32))
    dist = jnp.asarray(rng.integers(1, 8, GRAPH_EDGES, dtype=np.int32))
    emask = jnp.ones(GRAPH_EDGES, dtype=bool)
    req_count = jnp.asarray(rng.gamma(2.0, 100.0, N_SERVICES).astype(np.float32))
    err_count = req_count * 0.01
    cv_w = req_count * 0.5
    replicas = jnp.ones(N_SERVICES, dtype=jnp.float32)
    active = jnp.ones(N_SERVICES, dtype=bool)

    @jax.jit
    def refresh_chain():
        def body(_i, acc):
            s = scorers.service_scores(
                src,
                dst,
                dist,
                emask,
                ep_service,
                ep_ml,
                ep_record,
                num_services=N_SERVICES,
            )
            coh = scorers.usage_cohesion(
                src,
                dst,
                dist,
                emask,
                ep_service,
                ep_record,
                num_services=N_SERVICES,
            )
            risk = scorers.risk_scores(
                s.relying_factor,
                s.acs,
                replicas,
                req_count + acc * 1e-12,
                err_count,
                cv_w,
                active,
            )
            return acc + digest(tuple(s)) + digest(tuple(coh)) + digest(tuple(risk))

        return jax.lax.fori_loop(0, ITERS, body, 0.0)

    # latency metric: median (a p50 claim is about the typical run)
    refresh_total = _timed_median(lambda: float(refresh_chain()), reps=7)
    refresh_ms = max(refresh_total - rtt, 0.0) / ITERS * 1000

    # ---- scorers AT THE HTTP SURFACE (VERDICT r1 #2) -----------------------
    # real ApiServer + GraphHandler served from a 10k-endpoint device graph:
    # what an API consumer actually waits for on GET /graph/instability
    import urllib.request as _urlreq

    from kmamiz_tpu.api.app import build_router
    from kmamiz_tpu.api.router import ApiServer
    from kmamiz_tpu.config import Settings
    from kmamiz_tpu.core.interning import EndpointInterner
    from kmamiz_tpu.graph.store import EndpointGraph
    from kmamiz_tpu.ops.sortutil import SENTINEL
    from kmamiz_tpu.server.initializer import AppContext, Initializer
    from kmamiz_tpu.server.processor import DataProcessor
    from kmamiz_tpu.server.storage import MemoryStore

    interner = EndpointInterner()
    for e in range(N_ENDPOINTS):
        svc = e % N_SERVICES
        interner.intern_endpoint(
            f"svc{svc}\tns{svc % 8}\tv1\tGET\thttp://svc{svc}/api/ep{e}",
            {"uniqueEndpointName": f"ep{e}", "timestamp": 0},
        )
    big_graph = EndpointGraph(interner=interner, capacity=_pow2(GRAPH_EDGES))
    ecap = big_graph.capacity
    e_src = np.full(ecap, SENTINEL, dtype=np.int32)
    e_dst = np.full(ecap, SENTINEL, dtype=np.int32)
    e_dist = np.full(ecap, SENTINEL, dtype=np.int32)
    e_src[:GRAPH_EDGES] = rng.integers(0, N_ENDPOINTS, GRAPH_EDGES)
    e_dst[:GRAPH_EDGES] = rng.integers(0, N_ENDPOINTS, GRAPH_EDGES)
    e_dist[:GRAPH_EDGES] = rng.integers(1, 8, GRAPH_EDGES)
    big_graph._src = jnp.asarray(e_src)
    big_graph._dst = jnp.asarray(e_dst)
    big_graph._dist = jnp.asarray(e_dist)
    big_graph._n_edges = GRAPH_EDGES
    big_graph._ensure_ep_arrays(N_ENDPOINTS)
    big_graph._ep_record[:] = True

    api_settings = Settings()
    api_settings.external_data_processor = ""
    dp = DataProcessor(trace_source=lambda lb, t, lim: [])
    dp.graph = big_graph
    ctx = AppContext.build(
        app_settings=api_settings, store=MemoryStore(), processor=dp
    )
    Initializer(ctx).register_data_caches()
    api = ApiServer(build_router(ctx), host="127.0.0.1", port=0)
    api.start()
    try:
        url = f"http://127.0.0.1:{api.port}/api/v1/graph/instability"

        def http_get():
            with _urlreq.urlopen(url) as r:
                assert r.status == 200
                r.read()

        http_api_refresh_ms = _timed_median(http_get, reps=5) * 1000
    finally:
        api.stop()

    # ---- graph-store scaling: 100k endpoints / ~5M edges -------------------
    # characterizes the capacity-doubling policy past the 10k-endpoint
    # operating point (VERDICT r3 #6): per-union merge wall through the
    # doublings, distinct compiled union programs, and the scorer
    # refresh at the final scale. Edge batches are generated ON DEVICE
    # (the tunnel would add minutes of copy otherwise); the union runs
    # the store's real merge kernel + capacity policy via merge_edges.
    # compile-cost context (measured once on this setup, 2026-07-30): each
    # union program compiles in ~50-70 s over the dev tunnel and there are
    # only ~3 across the whole growth (capacities double); the 100k-scale
    # scorer program compiles in ~4.5 min at 8M-wide arrays (~10 min with
    # cohesion included). The refresh here therefore measures the
    # BASELINE-worded "risk+instability refresh" on the 4M-capacity
    # snapshot, and a time-budget guard skips the whole section rather
    # than risk starving the headline artifact.
    scale_extras = {}
    bench_elapsed_s = time.perf_counter() - BENCH_T0
    try:
        bench_budget_s = int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000))
    except ValueError:
        bench_budget_s = 3000
    run_scale = (
        os.environ.get("KMAMIZ_BENCH_SCALE100K", "1") != "0"
        and bench_elapsed_s < bench_budget_s - 600
    )
    if not run_scale:
        scale_extras["graph_scale_skipped"] = (
            "disabled" if os.environ.get("KMAMIZ_BENCH_SCALE100K") == "0"
            else f"time budget ({bench_elapsed_s:.0f}s elapsed)"
        )
    else:
        # transient tunnel/compile failures in this OPTIONAL section
        # must degrade to an extras note, not kill the whole bench
        # artifact (the driver records the one JSON line)
        try:
            from kmamiz_tpu.graph.store import EndpointGraph, _merge_edges

            N_EP_BIG = 100_000
            N_SVC_BIG = 10_000
            STEP = 1 << 20  # ~1M candidate edges per union, fixed shape
            STEPS = 5  # ~5.2M distinct edges by the end

            big = EndpointGraph(capacity=1 << 20)
            key = jax.random.PRNGKey(7)

            merge_walls = []
            caps = []
            refresh_snapshot = None
            for step in range(STEPS):
                key, k1, k2, k3 = jax.random.split(key, 4)
                src_b = jax.random.randint(k1, (STEP,), 0, N_EP_BIG, jnp.int32)
                dst_b = jax.random.randint(k2, (STEP,), 0, N_EP_BIG, jnp.int32)
                dist_b = jax.random.randint(k3, (STEP,), 1, 8, jnp.int32)
                jax.block_until_ready([src_b, dst_b, dist_b])
                t0 = time.perf_counter()
                big.merge_edges(src_b, dst_b, dist_b)
                n_after = big.n_edges  # drains the deferred count
                merge_walls.append(round((time.perf_counter() - t0) * 1000, 1))
                caps.append(int(big.capacity))
                if refresh_snapshot is None and int(big.capacity) >= (1 << 22):
                    # scorer-refresh point: the 4M-capacity store (the 8M-wide
                    # final arrays compile ~2x longer for the same per-edge
                    # answer; millions of real edges at 100k endpoints)
                    refresh_snapshot = (big.edge_arrays(), n_after)
            scale_extras = {
                "graph_scale_endpoints": N_EP_BIG,
                "graph_scale_edges_final": int(big.n_edges),
                "graph_scale_capacities": caps,
                "graph_scale_merge_walls_ms": merge_walls,
                # distinct compiled union programs across the WHOLE bench run
                # (10k section + this growth curve): the capacity policy's
                # compile bill
                "graph_scale_union_programs": int(_merge_edges._cache_size()),
            }

            # risk+instability refresh at the 100k-endpoint scale (the
            # BASELINE target's wording; chained + rtt-adjusted like the 10k
            # metric, which also folds in cohesion — its one-off 100k cost:
            # ~2.5 s/refresh, scorer compile ~10 min, measured 2026-07-30)
            (src_f, dst_f, dist_f, mask_f), snap_edges = refresh_snapshot
            # the store's tracked dist bounds -> the sparse scorer's static
            # promise (3 here: merged dists are 1..7); None keeps the
            # legacy lexsort path, so the metric reflects whichever
            # backend KMAMIZ_SPARSE selects
            dist_bits_big = big._scorer_dist_bits()
            ep_service_b = jnp.asarray(
                rng.integers(0, N_SVC_BIG, N_EP_BIG, dtype=np.int32)
            )
            ep_ml_b = jnp.asarray(rng.integers(0, 65536, N_EP_BIG, dtype=np.int32))
            ep_record_b = jnp.ones(N_EP_BIG, dtype=bool)
            replicas_b = jnp.ones(N_SVC_BIG, dtype=jnp.float32)
            req_b = jnp.asarray(
                rng.gamma(2.0, 100.0, N_SVC_BIG).astype(np.float32)
            )
            SCALE_ITERS = 4

            @jax.jit
            def refresh_chain_big():
                def body(_i, acc):
                    s = scorers.service_scores(
                        src_f,
                        dst_f,
                        dist_f,
                        mask_f,
                        ep_service_b,
                        ep_ml_b,
                        ep_record_b,
                        num_services=N_SVC_BIG,
                        dist_bits=dist_bits_big,
                    )
                    risk = scorers.risk_scores(
                        s.relying_factor,
                        s.acs,
                        replicas_b,
                        req_b + acc * 1e-12,
                        req_b * 0.01,
                        req_b * 0.5,
                        jnp.ones(N_SVC_BIG, dtype=bool),
                    )
                    return acc + digest(tuple(s)) + digest(tuple(risk))

                return jax.lax.fori_loop(0, SCALE_ITERS, body, 0.0)

            refresh_big_total = _timed_median(
                lambda: float(refresh_chain_big()), reps=3
            )
            scale_extras["graph_refresh_ms_100k"] = round(
                max(refresh_big_total - rtt, 0.0) / SCALE_ITERS * 1000, 2
            )
            scale_extras["graph_refresh_100k_edges"] = int(snap_edges)
            del big, src_f, dst_f, dist_f, mask_f
        except Exception as err:  # noqa: BLE001 - optional section
            scale_extras["graph_scale_error"] = f"{type(err).__name__}: {err}"[:300]
            # the success path dels the multi-million-row arrays; a
            # mid-section failure must not leave them pinned for the
            # remaining sections on this 1-core box (refresh_snapshot
            # aliases the same edge_arrays tuple; the per-service inputs
            # and the jitted closure keep device buffers alive too)
            big = src_f = dst_f = dist_f = mask_f = None  # noqa: F841
            refresh_snapshot = None  # noqa: F841
            ep_service_b = ep_ml_b = ep_record_b = None  # noqa: F841
            replicas_b = req_b = None  # noqa: F841
            refresh_chain_big = None  # noqa: F841 - closure pins the arrays

    # ---- capacity growth: repack vs segment-append A/B ---------------------
    # one capacity doubling on a small warm store under each growth mode
    # (KMAMIZ_STORE_GROW). The repack crossing recompiles graph.fit_edges
    # at the doubled width; the segment crossing re-splits into the
    # always-present overflow tail with zero new programs. The wall-clock
    # gap IS the compile bill the segment policy removes from the hot
    # path — tiny here (2k-wide arrays on CPU), ~a minute per program at
    # the 100k scale over the dev tunnel (see the scale section notes).
    grow_extras = {
        "graph_capacity_grow_ms": None,
        "graph_capacity_grow_repack_ms": None,
    }
    try:
        GROW_ROWS, GROW_BATCHES = 300, 4  # 3 warm merges, 4th crosses 1024

        def _grow_batches():
            # globally-distinct (src, dst) pairs so dedup never collapses
            # the count: 1200 edges after batch 4 > cap 1024, within the
            # 256-row tail (no consolidation; repack doubles to 2048)
            for i in range(GROW_BATCHES):
                k = np.arange(i * GROW_ROWS, (i + 1) * GROW_ROWS)
                yield (
                    (k % 797).astype(np.int32),
                    (k // 797).astype(np.int32),
                    np.full(GROW_ROWS, 1 + i % 7, dtype=np.int32),
                )

        for mode, grow_key in (
            ("repack", "graph_capacity_grow_repack_ms"),
            ("segment", "graph_capacity_grow_ms"),
        ):
            gg = EndpointGraph(capacity=1024, grow=mode)
            *warm, crossing = list(_grow_batches())
            for s_b, d_b, ds_b in warm:
                gg.merge_edges(s_b, d_b, ds_b)
                gg.n_edges  # drain the deferred count
            t0 = time.perf_counter()
            gg.merge_edges(*crossing)
            gg.n_edges
            grow_extras[grow_key] = round((time.perf_counter() - t0) * 1000, 2)
            del gg
        if grow_extras["graph_capacity_grow_ms"]:
            grow_extras["graph_capacity_grow_speedup"] = round(
                grow_extras["graph_capacity_grow_repack_ms"]
                / grow_extras["graph_capacity_grow_ms"],
                1,
            )
    except Exception as err:  # noqa: BLE001 - keys stay present, value None
        grow_extras["graph_capacity_grow_error"] = (
            f"{type(err).__name__}: {err}"[:300]
        )

    # ---- graftcost predictive prewarm: crossing stall A/B ------------------
    # the same segment-store consolidation, prewarm ON vs OFF, one
    # subprocess per arm (compile caches are process-global — an
    # in-process A/B would leak warmth from the first arm into the
    # second; the persistent XLA cache is disabled for both arms so OFF
    # really pays the compile). Identical ramps, asserted bit-exact.
    cost_extras = {
        "capacity_growth_stall_ms": None,
        "capacity_growth_stall_off_ms": None,
        "capacity_growth_stall_reduction": None,
        "capacity_growth_mid_compiles": None,
        "capacity_growth_bit_exact": None,
        "cost_prewarm_hit_rate": None,
    }
    try:
        import subprocess

        arms = {}
        for arm in ("off", "on"):
            probe_env = {
                **os.environ,
                "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
            }
            # both arms fully cold and hint-free: the OFF arm must
            # actually pay the crossing compile it is measuring
            probe_env.pop("KMAMIZ_COMPILE_CACHE_DIR", None)
            probe_env.pop("KMAMIZ_SHAPE_HINTS", None)
            proc = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "kmamiz_tpu.cost.growth_probe",
                    "--prewarm",
                    arm,
                ],
                cwd=str(Path(__file__).parent),
                env=probe_env,
                capture_output=True,
                text=True,
                timeout=600,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"growth probe ({arm}) rc={proc.returncode}: "
                    f"{proc.stderr[-300:]}"
                )
            arms[arm] = json.loads(proc.stdout.strip().splitlines()[-1])
        off_arm, on_arm = arms["off"], arms["on"]
        cost_extras["capacity_growth_stall_ms"] = on_arm["stall_ms"]
        cost_extras["capacity_growth_stall_off_ms"] = off_arm["stall_ms"]
        cost_extras["capacity_growth_stall_reduction"] = round(
            off_arm["stall_ms"] / max(on_arm["stall_ms"], 1e-9), 1
        )
        cost_extras["capacity_growth_mid_compiles"] = on_arm["mid_compiles"]
        cost_extras["capacity_growth_bit_exact"] = (
            off_arm["signature"] == on_arm["signature"]
        )
        cost_extras["cost_prewarm_hit_rate"] = on_arm.get("hit_rate")
        cost_extras["capacity_growth_steady_ms"] = on_arm.get("steady_ms")
    except Exception as err:  # noqa: BLE001 - keys stay present, value None
        cost_extras["capacity_growth_error"] = (
            f"{type(err).__name__}: {err}"[:300]
        )

    # ---- end-to-end DP tick at the reference's own scale -------------------
    # the reference caps realtime ticks at 2,500 traces / 5 s; this times the
    # FULL DataProcessor.collect (host parse + device kernels + response
    # assembly) on a 2,500-trace window, the product-level SLA
    from kmamiz_tpu.server.processor import DataProcessor

    base_spans = [
        {
            "traceId": "t0",
            "id": f"s{j}",
            "parentId": f"s{j-1}" if j else None,
            "kind": "SERVER" if j % 2 == 0 else "CLIENT",
            "name": f"svc{j % 5}.ns.svc.cluster.local:80/*",
            "timestamp": 1_700_000_000_000_000 + j,
            "duration": 1000 + j,
            "tags": {
                "http.method": "GET",
                "http.status_code": "200",
                "http.url": f"http://svc{j % 5}.ns.svc.cluster.local/api/{j % 7}",
                "istio.canonical_revision": "v1",
                "istio.canonical_service": f"svc{j % 5}",
                "istio.mesh_id": "cluster.local",
                "istio.namespace": "ns",
            },
        }
        for j in range(7)
    ]

    def tick_traces(tick_id):
        groups = []
        for t in range(2500):
            g = []
            for s in base_spans:
                c = dict(s)
                c["id"] = f"{tick_id}-{t}-{s['id']}"
                c["traceId"] = f"{tick_id}-t{t}"
                if c["parentId"]:
                    c["parentId"] = f"{tick_id}-{t}-{c['parentId']}"
                if t % 17 == 0 and s["kind"] == "SERVER":
                    c = {**c, "tags": {**c["tags"], "http.status_code": "503"}}
                g.append(c)
            groups.append(g)
        return groups

    # pre-generate every rep's window OUTSIDE the timed region: the metric
    # charges only DataProcessor.collect, not test-data synthesis. Four
    # timed legs below (cold, cached, telemetry-off, prof-off) each burn
    # 1 warmup + 5 reps = 24 windows.
    prebuilt = [tick_traces(i) for i in range(24)]

    def source(_lb, _t, _lim):
        return prebuilt.pop(0)

    dp = DataProcessor(trace_source=source, use_device_stats=True)
    rep_counter = {"n": 0}

    def one_tick():
        rep_counter["n"] += 1
        dp.collect(
            {"uniqueId": f"b{rep_counter['n']}", "lookBack": 30_000, "time": rep_counter["n"]}
        )

    # latency metric vs the reference's 5 s tick budget: median
    dp_tick_ms = _timed_median(one_tick, reps=5) * 1000  # first call warms

    # steady-state tick: same workload shape, but every warmable layer is
    # hot — endpoint-info/record templates, XLA executables, the graph's
    # device-resident scorer tables — i.e. production cadence after boot
    dp_tick_cached_ms = _timed_median(one_tick, reps=5) * 1000

    # telemetry overhead: the same warm tick with span tracing gated off
    # (KMAMIZ_TELEMETRY=0). The acceptance bound is tracing-on within 5%
    # of this number; both medians ride identical prebuilt windows
    _tel_prev = os.environ.get("KMAMIZ_TELEMETRY")
    os.environ["KMAMIZ_TELEMETRY"] = "0"
    try:
        dp_tick_telemetry_off_ms = _timed_median(one_tick, reps=5) * 1000
    finally:
        if _tel_prev is None:
            os.environ.pop("KMAMIZ_TELEMETRY", None)
        else:
            os.environ["KMAMIZ_TELEMETRY"] = _tel_prev

    # graftprof overhead proof: the same warm tick with the profiler
    # event ring gated off (KMAMIZ_PROF=0, tracing still ON). Acceptance:
    # the prof-on steady tick (dp_tick_cached_ms) within 3% of this.
    _prof_prev = os.environ.get("KMAMIZ_PROF")
    os.environ["KMAMIZ_PROF"] = "0"
    try:
        dp_tick_prof_off_ms = _timed_median(one_tick, reps=5) * 1000
    finally:
        if _prof_prev is None:
            os.environ.pop("KMAMIZ_PROF", None)
        else:
            os.environ["KMAMIZ_PROF"] = _prof_prev

    # per-phase attribution keys from the graftprof host event ring,
    # ALWAYS present (0.0 when a phase recorded nothing, so slo_report
    # can gate them across rounds without key-existence special cases).
    # One small native raw-ingest under a traced tick first, so the
    # native merge/lock-wait delta events have a sample at the deployed
    # parse-thread setting.
    from kmamiz_tpu.telemetry.profiling import events as prof_ring
    from kmamiz_tpu.telemetry.tracing import TRACER as _PROF_TRACER

    with _PROF_TRACER.tick(root_name="dp-ingest"):
        try:
            dp.ingest_raw_window(
                make_raw_window(200, 10, t_start=990_000, trace_prefix="prof-")
            )
        except ValueError:
            pass  # native loader absent: the prof keys report 0.0
    prof_phase_keys = {
        "prof_parse_ms_p95": prof_ring.phase_p95_ms("parse"),
        "prof_merge_lockwait_ms_p95": prof_ring.phase_p95_ms(
            "native-merge-lockwait"
        ),
        "prof_transfer_ms_p95": prof_ring.phase_p95_ms("host-transfer"),
        "prof_device_walk_ms_p95": prof_ring.phase_p95_ms("walk"),
        # sparse-walk attribution rides its own phase name (the processor
        # switches the walk span to "walk_sparse" under KMAMIZ_SPARSE) so
        # graftprof --diff can compare walk backends; 0.0 when the dense
        # walk served this run
        "prof_device_walk_sparse_ms_p95": prof_ring.phase_p95_ms(
            "walk_sparse"
        ),
        # graftstream freshness plane: arrival->visible watermark events
        # emitted by finish_tick (serial and stream paths both stamp);
        # p99 because the SLO is a tail bound, not a typical-case one
        "prof_freshness_ms_p99": prof_ring.phase_percentile_ms(
            "freshness", 0.99
        ),
    }

    # scorer read path between merges: the first read after a merge
    # computes (full or dirty-incremental), every repeated HTTP read is an
    # O(1) memo hit on (cache key, graph version)
    scorer_now_ms = float(dp._now_ms())
    dp.graph.service_scores(now_ms=scorer_now_ms)  # compute + fill memo
    scorer_cached_read_ms = (
        _timed_median(
            lambda: dp.graph.service_scores(now_ms=scorer_now_ms), reps=5
        )
        * 1000
    )
    scorer_stats = dp.graph.scorer_cache_stats()

    # ---- GraphSAGE training/serving (models/stacked.py + serving.py) -------
    # scan-fused epoch (ONE jitted lax.scan over device-resident stacked
    # slots) vs the legacy per-slot host loop it replaced, at the BASELINE
    # graph shape (1k svc / 10k endpoints / 50k edges, 24 hourly slots,
    # hidden=32), plus the served jitted forecast forward. Best-effort and
    # budget-guarded: a failure reports sage_error, never sinks the headline.
    sage_extras = {}
    try:
        sage_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 1500
        )
    except ValueError:
        sage_budget_ok = True
    if sage_budget_ok:
        try:
            from kmamiz_tpu.models import graphsage as sage_model
            from kmamiz_tpu.models import serving as sage_serving
            from kmamiz_tpu.models import stacked as sage_stacked
            from kmamiz_tpu.models import trainer as sage_trainer

            SAGE_S, SAGE_H, SAGE_EP = 24, 32, 8
            sage_rng = np.random.default_rng(11)
            sage_ds = sage_trainer.GraphDataset(
                endpoint_names=[f"ep{i}" for i in range(N_ENDPOINTS)],
                src=jnp.asarray(
                    sage_rng.integers(
                        0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32
                    )
                ),
                dst=jnp.asarray(
                    sage_rng.integers(
                        0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32
                    )
                ),
                edge_mask=jnp.ones(GRAPH_EDGES, dtype=bool),
                features=[
                    jnp.asarray(
                        sage_rng.normal(
                            size=(N_ENDPOINTS, sage_model.NUM_FEATURES)
                        ).astype(np.float32)
                    )
                    for _ in range(SAGE_S)
                ],
                target_latency=[
                    jnp.asarray(
                        sage_rng.normal(size=N_ENDPOINTS).astype(np.float32)
                    )
                    for _ in range(SAGE_S)
                ],
                target_anomaly=[
                    jnp.asarray(
                        (sage_rng.random(N_ENDPOINTS) < 0.1).astype(
                            np.float32
                        )
                    )
                    for _ in range(SAGE_S)
                ],
                node_mask=[
                    jnp.asarray(sage_rng.random(N_ENDPOINTS) < 0.95)
                    for _ in range(SAGE_S)
                ],
                slot_keys=[f"s{i}" for i in range(SAGE_S)],
            )
            sage_pw = 4.0
            sage_lr = 1e-2
            sage_p0 = sage_model.init_params(jax.random.PRNGKey(3), hidden=SAGE_H)
            sage_opt = sage_model.make_optimizer(sage_lr)

            # legacy per-slot host loop: one jitted step dispatch + host
            # loss fetch per slot per epoch — exactly trainer.train's
            # pre-fusion control flow
            sage_step = sage_model.make_train_step(sage_opt, pos_weight=sage_pw)
            lstate = {"p": sage_p0, "s": sage_opt.init(sage_p0)}

            def sage_legacy_epoch():
                p, s = lstate["p"], lstate["s"]
                for i in range(SAGE_S):
                    p, s, loss, _aux = sage_step(
                        p,
                        s,
                        sage_ds.features[i],
                        sage_ds.src,
                        sage_ds.dst,
                        sage_ds.edge_mask,
                        sage_ds.target_latency[i],
                        sage_ds.target_anomaly[i],
                        sage_ds.node_mask[i],
                    )
                    float(loss)
                lstate["p"], lstate["s"] = p, s

            sage_legacy_epoch_ms = _timed(sage_legacy_epoch, reps=2) * 1000

            # scan-fused: whole SAGE_EP-epoch block as ONE program over the
            # stacked device-resident dataset; params/opt state donated and
            # threaded across calls
            sage_st = sage_stacked.stack_dataset(sage_ds)
            sage_runner = sage_stacked.epoch_runner(sage_model, sage_lr, sage_pw)
            fstate = {"p": sage_p0, "s": sage_opt.init(sage_p0)}

            def sage_fused_block():
                p, s, block = sage_runner(
                    fstate["p"],
                    fstate["s"],
                    sage_st.features,
                    sage_st.target_latency,
                    sage_st.target_anomaly,
                    sage_st.node_mask,
                    sage_st.src,
                    sage_st.dst,
                    sage_st.edge_mask,
                    SAGE_EP,
                )
                jax.block_until_ready(block)
                fstate["p"], fstate["s"] = p, s

            sage_epoch_ms = _timed(sage_fused_block, reps=2) * 1000 / SAGE_EP

            # served inference: the jitted shape-stable forward behind
            # POST /model/forecast (bucket padding + upload + fetch charged)
            sage_feats_np = np.asarray(sage_ds.features[0])
            sage_src_np = np.asarray(sage_ds.src)
            sage_dst_np = np.asarray(sage_ds.dst)
            sage_mask_np = np.asarray(sage_ds.edge_mask)

            sage_infer_ms = (
                _timed_median(
                    lambda: sage_serving.forecast_forward(
                        fstate["p"],
                        sage_feats_np,
                        sage_src_np,
                        sage_dst_np,
                        sage_mask_np,
                        sage_model,
                    ),
                    reps=5,
                )
                * 1000
            )
            sage_extras = {
                "sage_epoch_ms": round(sage_epoch_ms, 1),
                "sage_epoch_legacy_ms": round(sage_legacy_epoch_ms, 1),
                "sage_fused_speedup": round(
                    sage_legacy_epoch_ms / max(sage_epoch_ms, 1e-9), 1
                ),
                "sage_train_slots_per_s": round(
                    SAGE_S / max(sage_epoch_ms / 1000.0, 1e-9), 1
                ),
                "sage_infer_ms": round(sage_infer_ms, 2),
                "sage_shape": {
                    "nodes": N_ENDPOINTS,
                    "edges": GRAPH_EDGES,
                    "slots": SAGE_S,
                    "hidden": SAGE_H,
                    "bucket_nodes": sage_st.bucket_nodes,
                    "bucket_edges": sage_st.bucket_edges,
                },
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            sage_extras = {"sage_error": str(err)}

    # ---- STLGT continual quantile model (ISSUE 10) -------------------------
    # the linear graph transformer's two hot-path latencies — the per-fold
    # train tick (observe_fold: window -> ring example + scan-fused
    # epoch-block refresh) and the served quantile forward behind
    # GET /model/forecast?quantile= — plus its p99 coverage from a short
    # prequential replay over scenario-factory labeled windows (the
    # tools/eval_stlgt.py methodology, compressed). The three keys are
    # ALWAYS present (None on skip/failure) so a regression can never
    # hide inside a missing key; KMAMIZ_BENCH_STLGT=0 skips. Gated by
    # tools/slo_report.py: the latency pair as higher-is-worse, the
    # coverage as a float floor.
    stlgt_extras = {
        "stlgt_train_tick_ms": None,
        "stlgt_infer_ms": None,
        "stlgt_p99_coverage": None,
    }
    try:
        stlgt_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 1400
        )
    except ValueError:
        stlgt_budget_ok = True
    if os.environ.get("KMAMIZ_BENCH_STLGT", "1") != "0" and stlgt_budget_ok:
        try:
            from kmamiz_tpu.models.stlgt import serving as stlgt_serving
            from kmamiz_tpu.models.stlgt.trainer import ContinualTrainer
            from kmamiz_tpu.scenarios import build_scenario, labeled_windows

            STLGT_TICKS, STLGT_WARMUP = 24, 4
            stlgt_data = labeled_windows(
                build_scenario("cascade-fanout", 0, 0, STLGT_TICKS)
            )
            stlgt_windows = stlgt_data["windows"]
            stlgt_trainer = ContinualTrainer(
                depth=8, refresh_every=1, epochs=2, hidden=16, lr=0.02
            )
            fold_walls = []
            stlgt_cov = []
            for t, w in enumerate(stlgt_windows):
                snap = {
                    "features": w["features"],
                    "src": stlgt_data["src"],
                    "dst": stlgt_data["dst"],
                    "mask": stlgt_data["mask"],
                    "names": stlgt_data["names"],
                    "predicted_hour": (t + 1) % 24,
                    "cache_key": (1, 0, t),
                }
                t0 = time.perf_counter()
                stlgt_trainer.observe_fold(snap)
                if t >= STLGT_WARMUP:
                    # ring bucket + epoch-block program are warm by now:
                    # these walls are the steady-state fold tick
                    fold_walls.append(time.perf_counter() - t0)
                live = stlgt_trainer.serving()
                if (
                    live is None
                    or t < STLGT_WARMUP
                    or t + 1 >= len(stlgt_windows)
                ):
                    continue
                nxt = stlgt_windows[t + 1]
                act = w["active"] & nxt["active"]
                if not act.any():
                    continue
                q_ms, _prob, _gate = stlgt_serving.quantile_forward(
                    live["params"],
                    w["features"],
                    stlgt_data["src"],
                    stlgt_data["dst"],
                    stlgt_data["mask"],
                    live["model"],
                )
                stlgt_cov.append(
                    float(np.mean(nxt["latency_ms"][act] <= q_ms[act, 2]))
                )

            # served inference: the jitted shape-stable quantile forward
            # behind the route (bucket padding + upload + fetch charged)
            stlgt_live = stlgt_trainer.serving()
            stlgt_last = stlgt_windows[-1]
            stlgt_infer_ms = (
                _timed_median(
                    lambda: stlgt_serving.quantile_forward(
                        stlgt_live["params"],
                        stlgt_last["features"],
                        stlgt_data["src"],
                        stlgt_data["dst"],
                        stlgt_data["mask"],
                        stlgt_live["model"],
                    ),
                    reps=5,
                )
                * 1000
            )
            stlgt_extras = {
                # fold tick and infer are latency metrics: median
                "stlgt_train_tick_ms": (
                    round(float(np.median(fold_walls)) * 1000, 2)
                    if fold_walls
                    else None
                ),
                "stlgt_infer_ms": round(stlgt_infer_ms, 2),
                "stlgt_p99_coverage": (
                    round(float(np.mean(stlgt_cov)), 4) if stlgt_cov else None
                ),
                "stlgt_scored_ticks": len(stlgt_cov),
                "stlgt_trainer": stlgt_trainer.status(),
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            stlgt_extras["stlgt_error"] = f"{type(err).__name__}: {err}"[:300]

    # ---- restart warmth (VERDICT r4 #5b) -----------------------------------
    # two fresh subprocesses share one persistent compilation cache dir:
    # run 1 pays the pre-warm compile walls into the cache, run 2 is the
    # production restart — pre-warm reloads from disk and the first tick
    # runs with zero compile exposure. Budget-guarded (each run re-pays
    # jax import + device handshake).
    warm_boot_extras = {}
    try:
        # headroom covers the worst case: two subprocess runs at their
        # full 600 s timeouts, plus margin for the result assembly
        warm_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 1300
        )
    except ValueError:
        warm_budget_ok = True
    if warm_budget_ok:
        import subprocess
        import tempfile

        with tempfile.TemporaryDirectory(prefix="kmamiz-xla-cache-") as d:
            env = {**os.environ, "KMAMIZ_COMPILE_CACHE_DIR": d}
            runs = []
            for tag in ("cold", "restart"):
                try:
                    out = subprocess.run(
                        [sys.executable, "tools/warm_boot_probe.py"],
                        env=env,
                        capture_output=True,
                        text=True,
                        timeout=600,
                    )
                    runs.append((tag, json.loads(out.stdout.strip().splitlines()[-1])))
                except Exception as err:  # noqa: BLE001 - extra, not headline
                    warm_boot_extras["warm_boot_error"] = f"{tag}: {err}"
                    break
            for tag, probe in runs:
                warm_boot_extras[f"warm_boot_{tag}_prewarm_s"] = probe["prewarm_s"]
                warm_boot_extras[f"warm_boot_{tag}_first_tick_ms"] = probe[
                    "first_tick_ms"
                ]
                # per-program compile counts the probe's ticks still paid
                # (program registry telemetry, core/programs.py): after a
                # hint-driven prewarm the restart run must report 0
                warm_boot_extras[f"warm_boot_{tag}_tick_compiles"] = probe.get(
                    "first_tick_new_compiles", 0
                ) + probe.get("second_tick_new_compiles", 0)
                warm_boot_extras[f"warm_boot_{tag}_prewarm_coverage"] = probe.get(
                    "prewarm_report", {}
                )
                warm_boot_extras[f"warm_boot_{tag}_programs"] = probe.get(
                    "programs", {}
                )
            if len(runs) == 2:
                warm_boot_extras["warm_first_tick_ms"] = runs[1][1][
                    "first_tick_ms"
                ]
                # restart contract: a warm process's first tick stays
                # within 2x the steady-state tick — the shape-hint prewarm
                # already replayed every (program, bucket) the previous
                # process compiled, so nothing traces inside the tick
                warm_boot_extras["warm_boot_first_tick_target_ms"] = round(
                    2 * runs[1][1]["second_tick_ms"], 1
                )
                warm_boot_extras["warm_boot_steady_state_recompiles"] = (
                    runs[1][1].get("first_tick_new_compiles", 0)
                    + runs[1][1].get("second_tick_new_compiles", 0)
                )

    # ---- chaos resilience (ISSUE 5) ----------------------------------------
    # one fresh subprocess runs tools/chaos_probe.py --seed 0: all four
    # fault-layer invariants (quarantine bit-exactness, breaker state
    # machine, stale-graph degradation, kill -9 -> WAL replay), plus the
    # two numbers reported here — kill -> bit-exact-restore wall time
    # and the latency of a degraded (stale) tick serve
    chaos_extras = {}
    try:
        chaos_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 700
        )
    except ValueError:
        chaos_budget_ok = True
    if chaos_budget_ok:
        import subprocess

        try:
            out = subprocess.run(
                [sys.executable, "tools/chaos_probe.py", "--seed", "0"],
                capture_output=True,
                text=True,
                timeout=600,
            )
            probe = json.loads(out.stdout.strip().splitlines()[-1])
            chaos_extras = {
                "chaos_probe_ok": probe["ok"],
                "chaos_recovery_ms": probe["chaos_recovery_ms"],
                "degraded_serve_ms": probe["degraded_serve_ms"],
                "chaos_quarantined": probe["quarantine"]["quarantined"],
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            chaos_extras = {"chaos_probe_error": str(err)}

    # ---- tenancy: stacked multi-tenant serving (ISSUE 7) -------------------
    # 8 same-bucket tenants, two claims: (1) the device stage the router
    # batches — window union + service scorers — is one stacked dispatch
    # instead of 8 serialized ones; (2) a 9th tenant joining the warm
    # bucket compiles NOTHING (shape-keyed module-level programs). The
    # four keys are ALWAYS present (None on skip/failure) so a regression
    # can never hide inside a missing key; KMAMIZ_BENCH_TENANCY=0 skips.
    tenancy_extras = {
        "tenant_batched_tick_ms_8": None,
        "tenant_serial_tick_ms_8": None,
        "tenant_batch_speedup": None,
        "tenant_join_compile_count": None,
    }
    try:
        tenancy_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 400
        )
    except ValueError:
        tenancy_budget_ok = True
    if os.environ.get("KMAMIZ_BENCH_TENANCY", "1") != "0" and tenancy_budget_ok:
        try:
            from kmamiz_tpu.core import programs
            from kmamiz_tpu.graph.store import (
                _edge_mask,
                _fit_edges,
                _merge_edges,
            )
            from kmamiz_tpu.ops import scorers as scorer_ops
            from kmamiz_tpu.ops.sortutil import SENTINEL as _SENT
            from kmamiz_tpu.server.processor import DataProcessor as _DP
            from kmamiz_tpu.tenancy import (
                TenantRuntime,
                TickRouter,
                batched_merge_edges,
                batched_service_scores,
            )

            # small-bucket shapes: fixture-scale tenants (the pdas mesh is
            # 3 services / ~a dozen edges) live in the smallest arena
            # bucket, where per-tick dispatch + sync overhead dominates —
            # exactly the regime tenant batching amortizes
            N_T = 8
            T_CAP, T_WCAP, T_EPCAP, T_NSVC = 32, 16, 64, 8
            rng = np.random.default_rng(7)

            def edge_cols(n_valid, cap, salt):
                src = np.full(cap, _SENT, dtype=np.int32)
                dst = np.full(cap, _SENT, dtype=np.int32)
                dist = np.full(cap, _SENT, dtype=np.int32)
                src[:n_valid] = rng.integers(0, T_EPCAP, n_valid) ^ salt
                dst[:n_valid] = rng.integers(0, T_EPCAP, n_valid)
                dist[:n_valid] = rng.integers(1, 8, n_valid)
                src[:n_valid] %= T_EPCAP
                return src, dst, dist

            stores = [edge_cols(24, T_CAP, t) for t in range(N_T)]
            windows = [edge_cols(10, T_WCAP, t + 100) for t in range(N_T)]
            ep_service = (
                np.arange(T_EPCAP, dtype=np.int32) % T_NSVC
            )
            ep_ml = np.arange(T_EPCAP, dtype=np.int32)
            ep_rec = np.ones(T_EPCAP, dtype=bool)

            def dev(cols):
                return [jax.device_put(a) for a in cols]

            st = [dev(c) for c in stores]
            wi = [dev(c) for c in windows]
            ep_s, ep_m, ep_r = dev((ep_service, ep_ml, ep_rec))
            stack = lambda i: jnp.stack([t[i] for t in st])
            wstack = lambda i: jnp.stack([w[i] for w in wi])
            S, D, DS = stack(0), stack(1), stack(2)
            WS, WD, WDS = wstack(0), wstack(1), wstack(2)
            M, WM = S != _SENT, WS != _SENT
            ep_S = jnp.stack([ep_s] * N_T)
            ep_M = jnp.stack([ep_m] * N_T)
            ep_R = jnp.stack([ep_r] * N_T)

            def serial_round():
                # one full blocking tick per tenant, exactly like the
                # router's serial fallback: merge, fetch the valid count
                # (_apply_merged's capacity policy), re-fit to the bucket,
                # score, then pull every ServiceScores field to host for
                # response building — the NEXT tenant's tick cannot start
                # until this one's response is materialized
                for t in range(N_T):
                    s, d, ds, v = _merge_edges(
                        st[t][0], st[t][1], st[t][2], _edge_mask(st[t][0]),
                        wi[t][0], wi[t][1], wi[t][2], _edge_mask(wi[t][0]),
                    )
                    int(jax.device_get(v.sum()))
                    s, d, ds = _fit_edges(s, d, ds, cap=T_CAP)
                    sc = scorer_ops.service_scores(
                        s, d, ds, _edge_mask(s), ep_s, ep_m, ep_r,
                        num_services=T_NSVC,
                    )
                    for f in sc:
                        jax.device_get(f)

            def batched_round():
                # ONE stacked dispatch for all 8 tenants: one count-vector
                # fetch, one stacked-tuple fetch
                s, d, ds, v, c = batched_merge_edges(
                    S, D, DS, M, WS, WD, WDS, WM
                )
                jax.device_get(c)
                sc = batched_service_scores(
                    s, d, ds, v, ep_S, ep_M, ep_R, num_services=T_NSVC
                )
                jax.device_get(sc)

            serial_ms = _timed_median(serial_round, reps=7) * 1000
            batched_ms = _timed_median(batched_round, reps=7) * 1000
            tenancy_extras["tenant_serial_tick_ms_8"] = round(serial_ms, 2)
            tenancy_extras["tenant_batched_tick_ms_8"] = round(batched_ms, 2)
            tenancy_extras["tenant_batch_speedup"] = round(
                serial_ms / max(batched_ms, 1e-9), 2
            )

            # zero-compile join: warm a bucket with 8 real tenant ticks,
            # then run a brand-new 9th tenant's FULL collect and diff the
            # program registry's compile counters
            join_spans = [
                [
                    {
                        "traceId": "j{}",
                        "id": "a",
                        "parentId": None,
                        "kind": "SERVER",
                        "name": f"svc{k}.ns.svc.cluster.local:80/*",
                        "timestamp": 1_700_000_000_000_000,
                        "duration": 900,
                        "tags": {
                            "http.method": "GET",
                            "http.status_code": "200",
                            "http.url": f"http://svc{k}.ns/api",
                            "istio.canonical_revision": "v1",
                            "istio.canonical_service": f"svc{k}",
                            "istio.mesh_id": "cluster.local",
                            "istio.namespace": "ns",
                        },
                    }
                ]
                for k in range(3)
            ]

            def join_source(tenant):
                tick = {"n": 0}

                def source(_lb, _t, _lim):
                    tick["n"] += 1
                    out = []
                    for g in join_spans:
                        c = [dict(s) for s in g]
                        for s in c:
                            s["traceId"] = f"{tenant}-{tick['n']}-{s['traceId']}"
                            s["id"] = f"{tenant}-{tick['n']}-{s['id']}"
                        out.append(c)
                    return out

                return source

            jrouter = TickRouter(
                lambda tenant: TenantRuntime(
                    tenant=tenant,
                    processor=_DP(
                        trace_source=join_source(tenant),
                        k8s_source=None,
                        use_device_stats=False,
                        tenant=tenant,
                    ),
                )
            )
            jreq = lambda i: {
                "uniqueId": f"j{i}", "lookBack": 30_000, "time": 1_700_000_000_000
            }
            jrouter.batched_collect(
                [(f"bench-t{t}", jreq(t)) for t in range(N_T)]
            )
            compiles_before = programs.summary()["totalCompiles"]
            jrouter.batched_collect([("bench-joiner", jreq(99))])
            tenancy_extras["tenant_join_compile_count"] = (
                programs.summary()["totalCompiles"] - compiles_before
            )
        except Exception as err:  # noqa: BLE001 - extra, not headline
            tenancy_extras["tenancy_error"] = (
                f"{type(err).__name__}: {err}"[:300]
            )

    # ---- scenarios: closed-loop soak matrix (ISSUE 8) ----------------------
    # one fresh subprocess runs the first three archetypes of the seeded
    # scenario matrix (tools/scenario_soak.py) against a real DP server:
    # steady chain, cascading fan-out failure, and the multi-tenant mix
    # (breaker flap + poison storm). The four keys are ALWAYS present
    # (None on skip/failure) and gated by tools/slo_report.py;
    # KMAMIZ_BENCH_SCENARIOS=0 skips.
    scenario_extras = {
        "scenario_matrix_pass": None,
        "scenario_worst_p99_tick_ms": None,
        "scenario_worst_recovery_ms": None,
        "scenario_lost_spans": None,
    }
    try:
        scenario_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 300
        )
    except ValueError:
        scenario_budget_ok = True
    if (
        os.environ.get("KMAMIZ_BENCH_SCENARIOS", "1") != "0"
        and scenario_budget_ok
    ):
        import subprocess

        try:
            out = subprocess.run(
                [
                    sys.executable,
                    "tools/scenario_soak.py",
                    "--seed",
                    "0",
                    "--matrix",
                    "3",
                    "--ticks",
                    "6",
                ],
                capture_output=True,
                text=True,
                timeout=600,
            )
            soak = json.loads(out.stdout.strip().splitlines()[-1])
            scenario_extras = {
                "scenario_matrix_pass": soak["scenario_matrix_pass"],
                "scenario_worst_p99_tick_ms": soak[
                    "scenario_worst_p99_tick_ms"
                ],
                "scenario_worst_recovery_ms": soak[
                    "scenario_worst_recovery_ms"
                ],
                "scenario_lost_spans": soak["scenario_lost_spans"],
                "scenario_matrix_size": len(soak["scenarios"]),
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            scenario_extras["scenario_soak_error"] = (
                f"{type(err).__name__}: {err}"[:300]
            )

    # ---- graftsoak sweep smoke (ROADMAP item 4 / docs/SCENARIOS.md) --------
    # one budget-guarded mini-sweep in a fresh tools/graftsoak.py
    # subprocess: a handful of cost-ordered cells across 2 workers plus
    # ONE seeded poison cell, proving the whole soak stack — manifest,
    # claims, namespaced flight boxes, baseline bisection, triage
    # dedupe — fires end to end every bench round. The three keys are
    # ALWAYS present (None on skip/failure) and gated by
    # tools/slo_report.py (pass-rate + triaged-fraction floors);
    # KMAMIZ_BENCH_SOAK=0 skips.
    soak_extras = {
        "soak_smoke_pass_rate": None,
        "soak_triaged_fraction": None,
        "soak_cells_per_min": None,
    }
    try:
        soak_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 290
        )
    except ValueError:
        soak_budget_ok = True
    if os.environ.get("KMAMIZ_BENCH_SOAK", "1") != "0" and soak_budget_ok:
        import subprocess
        import tempfile

        try:
            with tempfile.TemporaryDirectory(
                prefix="kmamiz-bench-soak-"
            ) as soak_dir:
                out = subprocess.run(
                    [
                        sys.executable,
                        "tools/graftsoak.py",
                        "--cells",
                        "5",
                        "--ticks",
                        "4",
                        "--workers",
                        "2",
                        "--poison",
                        "1",
                        "--soak-dir",
                        soak_dir,
                    ],
                    capture_output=True,
                    text=True,
                    timeout=600,
                )
                sweep = json.loads(out.stdout.strip().splitlines()[-1])
            soak_extras = {
                "soak_smoke_pass_rate": sweep["soak_pass_rate"],
                "soak_triaged_fraction": sweep["soak_triaged_fraction"],
                "soak_cells_per_min": sweep["soak_cells_per_min"],
                "soak_smoke_cells": sweep["cells_total"],
                "soak_smoke_bugs": len(sweep["bugs"]),
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            soak_extras["soak_error"] = f"{type(err).__name__}: {err}"[:300]

    # ---- graftfleet scale-out (ROADMAP item 2 / docs/FLEET.md) -------------
    # tools/fleet_bench.py in a fresh subprocess: four real worker
    # processes behind HTTPTransport — single-worker vs 4-worker ingest
    # rate, per-worker efficiency, and one live migration with a frame
    # injected mid-handoff. The six keys are ALWAYS present (None on
    # skip/failure) and gated by tools/slo_report.py: lost spans as
    # higher-is-worse, migration pass as a bool, the rate/efficiency
    # pair as floors plus the host-core-guarded absolute efficiency
    # check. KMAMIZ_BENCH_FLEET=0 skips.
    fleet_extras = {
        "fleet_spans_per_sec_1": None,
        "fleet_spans_per_sec_4": None,
        "fleet_scale_efficiency": None,
        "fleet_migration_lost_spans": None,
        "fleet_migration_pass": None,
        "fleet_host_cores": os.cpu_count(),
    }
    try:
        fleet_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 275
        )
    except ValueError:
        fleet_budget_ok = True
    if os.environ.get("KMAMIZ_BENCH_FLEET", "1") != "0" and fleet_budget_ok:
        import subprocess

        try:
            out = subprocess.run(
                [sys.executable, "tools/fleet_bench.py", "--frames", "16"],
                capture_output=True,
                text=True,
                timeout=900,
            )
            fleet_extras.update(
                json.loads(out.stdout.strip().splitlines()[-1])
            )
        except Exception as err:  # noqa: BLE001 - extra, not headline
            fleet_extras["fleet_bench_error"] = (
                f"{type(err).__name__}: {err}"[:300]
            )

    # ---- graftpilot control plane (ISSUE 11) -------------------------------
    # the controller's two latencies — the fold-boundary decision
    # recompute (Controller.ingest over synthetic forecast views) and the
    # serving-edge admission read the POST handler pays per tick — plus
    # the counterfactual gate's prevented-violation count from a fresh
    # tools/scenario_soak.py --counterfactual subprocess. The three keys
    # are ALWAYS present (None on skip/failure); KMAMIZ_BENCH_CONTROL=0
    # skips. Gated by tools/slo_report.py: the latency pair as
    # higher-is-worse, the prevented count as a float floor.
    control_extras = {
        "control_decision_ms": None,
        "control_tick_overhead_ms": None,
        "control_counterfactual_prevented": None,
    }
    try:
        control_budget_ok = (
            time.perf_counter() - BENCH_T0
            < int(os.environ.get("KMAMIZ_BENCH_BUDGET_S", 3000)) - 250
        )
    except ValueError:
        control_budget_ok = True
    if (
        os.environ.get("KMAMIZ_BENCH_CONTROL", "1") != "0"
        and control_budget_ok
    ):
        import subprocess

        try:
            from kmamiz_tpu import control as ctl_plane

            saved_ctl = {
                k: os.environ.get(k)
                for k in ("KMAMIZ_CONTROL", "KMAMIZ_CONTROL_SLO_MS")
            }
            os.environ["KMAMIZ_CONTROL"] = "1"
            os.environ["KMAMIZ_CONTROL_SLO_MS"] = "250"
            try:
                ctl_plane.reset_for_tests()
                decide_walls = []
                for i in range(64):
                    view = ctl_plane.ForecastView(
                        tenant="bench",
                        p99_ms=120.0 + (i % 7) * 40.0,
                        cost_ms=900.0 + i,
                        attributions=(
                            ("svc-a", "svc-b", 0.4 + (i % 3) * 0.2),
                        ),
                    )
                    t0 = time.perf_counter()
                    ctl_plane.ingest_forecast(view)
                    decide_walls.append((time.perf_counter() - t0) * 1000)
                # the admission read is sub-µs: time a 1000-call loop and
                # charge the mean per call (single-call walls are all
                # clock resolution)
                tick_req = {"uniqueId": "bench", "lookBack": 30_000}
                reads = 1000
                t0 = time.perf_counter()
                for _ in range(reads):
                    ctl_plane.admission_verdict("bench", tick_req)
                overhead_ms = (time.perf_counter() - t0) * 1000 / reads
            finally:
                for k, v in saved_ctl.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                ctl_plane.reset_for_tests()

            cf_out = subprocess.run(
                [
                    sys.executable,
                    "tools/scenario_soak.py",
                    "--counterfactual",
                    "--seed",
                    "0",
                    "--ticks",
                    "8",
                ],
                capture_output=True,
                text=True,
                timeout=600,
            )
            cf = json.loads(cf_out.stdout.strip().splitlines()[-1])
            control_extras = {
                "control_decision_ms": round(
                    float(np.median(decide_walls)), 4
                ),
                "control_tick_overhead_ms": round(overhead_ms, 5),
                "control_counterfactual_prevented": cf[
                    "control_counterfactual_prevented"
                ],
                "control_counterfactual_pass": cf["counterfactual_pass"],
            }
        except Exception as err:  # noqa: BLE001 - extra, not headline
            control_extras["control_error"] = (
                f"{type(err).__name__}: {err}"[:300]
            )

    e2e_extras = {}
    headline = None
    if e2e_phases is not None:
        parse_s, pack_s, transfer_s, device_s = e2e_phases
        work_s = parse_s + pack_s + device_s  # framework work
        total_s = work_s + transfer_s
        # the host->device copy rides the dev harness's TPU tunnel
        # (~10 MB/s vs PCIe's GB/s on a real TPU VM); all serial-path
        # numbers charge every framework phase and exclude ONLY that
        # tunnel copy, which is reported alongside
        e2e_spans_per_sec = e2e_n_spans / work_s
        e2e_extras = {
            "e2e_serial_spans_per_sec": round(e2e_spans_per_sec, 0),
            "e2e_incl_tunnel_spans_per_sec": round(e2e_n_spans / total_s, 0),
            "e2e_parse_ms": round(parse_s * 1000, 1),
            "e2e_pack_ms": round(pack_s * 1000, 1),
            "e2e_tunnel_transfer_ms": round(transfer_s * 1000, 1),
            "e2e_device_ms": round(device_s * 1000, 1),
            "e2e_serial_work_reps_ms": e2e_work_reps_ms,
            # cross-round continuity: BENCH_r03's e2e_spans_per_sec (the
            # last parseable pre-rework round, same serial tunnel-excluded
            # accounting). r03 ran on a TPU v5 lite harness — when the
            # current box differs (r06 is CPU-only), this ratio reflects
            # hardware as much as code; the same-box seed remeasure lives
            # in the artifact wrapper's seed_remeasure block
            "e2e_vs_seed_r03_serial": round(e2e_spans_per_sec / 193_988.0, 2),
            "parse_thread_scaling_1core": parse_scaling,
            **wire_extras,
        }
        if stream_best is not None:
            cp_ms, wall_s, summary = stream_best
            # the stream's OWN measured span count (dedup/odd-divisor safe)
            stream_rate = summary["spans"] / (cp_ms / 1000.0)
            headline = {
                "metric": (
                    "END-TO-END pipelined span ingest on the deployed route: "
                    "paginated raw Zipkin JSON -> DataProcessor."
                    "ingest_raw_stream (chunked native parse overlapping "
                    "device window-merge into the persistent endpoint "
                    "graph) — 1.05M-span window at BASELINE shape (1k "
                    "services / 10k endpoints / >=100k distinct edges), "
                    "steady-state persistent processor; tunnel copy "
                    "excluded via measured-phase critical path, see extras"
                ),
                "value": round(stream_rate, 0),
                "vs_baseline": round(stream_rate / BASELINE_SPANS_PER_SEC, 3),
            }
            # incl-tunnel follows the same best-of-N policy as every
            # throughput number (min measured wall, not the best-CP
            # rep's wall — tunnel throughput varies independently)
            best_wall_ms = min(stream_walls_ms)
            e2e_extras.update(
                {
                    "e2e_stream_spans_per_sec": round(stream_rate, 0),
                    "e2e_stream_spans_per_sec_incl_tunnel": round(
                        summary["spans"] / (best_wall_ms / 1000.0), 0
                    ),
                    "e2e_stream_critical_path_ms": round(cp_ms, 1),
                    "e2e_stream_wall_ms": round(wall_s * 1000, 1),
                    "e2e_stream_chunks": N_CHUNKS,
                    "e2e_stream_pipeline_depth": summary.get("pipeline_depth"),
                    "e2e_stream_ring_peak": summary.get("ring_peak"),
                    "e2e_stream_drain_ms": summary["drain_ms"],
                    "e2e_stream_chunk_detail": summary["chunk_detail"],
                    "e2e_stream_cp_reps_ms": stream_cp_ms,
                    "e2e_stream_wall_reps_ms": stream_walls_ms,
                    "e2e_stream_edges": summary["edges"],
                    "e2e_stream_endpoints": summary["endpoints"],
                    **stream_cold_extras,
                    **stream_legacy_extras,
                    **stream_upload_extras,
                }
            )
        else:  # streaming unavailable: serial e2e carries the headline
            headline = {
                "metric": (
                    "END-TO-END span ingest: raw Zipkin JSON bytes -> native "
                    "SoA loader -> intern/pack -> window stats + MXU "
                    "dependency walk -> fetch (1.05M-span window; tunnel "
                    "copy excluded, see extras)"
                ),
                "value": round(e2e_spans_per_sec, 0),
                "vs_baseline": round(
                    e2e_spans_per_sec / BASELINE_SPANS_PER_SEC, 3
                ),
            }
    if headline is None:  # native loader unavailable: device-chain number
        headline = {
            "metric": "span ingest throughput (window stats + MXU dependency walk, 1M-span window)",
            "value": round(spans_per_sec, 0),
            "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
        }
    # static-analysis cost: one full graftlint pass over the package
    # (what the tier-1 repo-clean test and --strict CI pay)
    t0 = time.perf_counter()
    from kmamiz_tpu.analysis import framework as lint_framework

    lint_result = lint_framework.lint_repo()
    graftlint_repo_ms = (time.perf_counter() - t0) * 1000

    # graftrace: the 3 concurrency rules alone (lock-model build is the
    # dominant cost; tools/graftrace.py --strict runs exactly this)
    from tools.graftrace import CONCURRENCY_RULES

    t0 = time.perf_counter()
    trace_result = lint_framework.lint_repo(list(CONCURRENCY_RULES))
    graftrace_repo_ms = (time.perf_counter() - t0) * 1000

    # SLO scorecard over this run's DP ticks (telemetry/slo.py): bench is
    # the first consumer of the headline keys ROADMAP item 5 asks for;
    # tools/slo_report.py --check gates regressions against these
    from kmamiz_tpu.telemetry import slo as tel_slo

    slo_extras = {
        f"slo_{k}": v for k, v in tel_slo.SCORECARD.snapshot().items()
    }

    result = {
        **headline,
        "unit": "spans/sec",
        "graftlint_repo_ms": round(graftlint_repo_ms, 1),
        "graftlint_findings": len(lint_result.findings),
        "graftlint_suppressed": len(lint_result.suppressed),
        "graftrace_repo_ms": round(graftrace_repo_ms, 1),
        "graftrace_findings": len(trace_result.findings),
        "graftrace_suppressed": len(trace_result.suppressed),
        "device_chain_spans_per_sec": round(spans_per_sec, 0),
        **e2e_extras,
        "e2e_bytes_per_span": round(e2e_bytes_per_span, 0),
        "e2e_host_cores": os.cpu_count(),
        "p50_graph_refresh_ms_10k_endpoints": round(refresh_ms, 2),
        **scale_extras,
        # graph-scale headline keys (ROADMAP item 2): always present, None
        # when the optional 100k section was skipped or failed, so a
        # regression can never hide inside a missing key
        "graph_refresh_ms_100k": scale_extras.get("graph_refresh_ms_100k"),
        "graph_merge_wall_ms_100k": (
            max(scale_extras["graph_scale_merge_walls_ms"])
            if scale_extras.get("graph_scale_merge_walls_ms")
            else None
        ),
        "graph_refresh_pass": bool(refresh_ms <= 50.0),
        **grow_extras,
        **cost_extras,
        "http_instability_10k_endpoints_ms": round(http_api_refresh_ms, 1),
        "walk_mxu_packed_ms": round(walk_mxu_ms, 1),
        "walk_flat_gather_ms": round(walk_flat_ms, 1),
        "walk_mxu_speedup": round(walk_flat_ms / max(walk_mxu_ms, 1e-9), 1),
        "walk_sharded_packed_1dev_ms": round(walk_sharded_packed_ms, 1),
        "walk_sharded_flat_1dev_ms": round(walk_sharded_flat_ms, 1),
        "graph_refresh_target_ms": 50.0,
        "n_spans": N_SPANS,
        "n_endpoints": N_ENDPOINTS,
        "n_services": N_SERVICES,
        "dp_tick_ms_2500_traces": round(dp_tick_ms, 1),
        "dp_tick_cached_ms": round(dp_tick_cached_ms, 1),
        "dp_tick_telemetry_off_ms": round(dp_tick_telemetry_off_ms, 1),
        "dp_tick_prof_off_ms": round(dp_tick_prof_off_ms, 1),
        **prof_phase_keys,
        **stream_tick_extras,
        **slo_extras,
        "dp_scorer_cached_read_ms": round(scorer_cached_read_ms, 3),
        "dp_scorer_cache_hit_rate": scorer_stats.get("hit_rate"),
        "dp_scorer_cache_stats": scorer_stats,
        "dp_tick_budget_ms": 5000.0,  # the reference's realtime cadence
        **sage_extras,
        **stlgt_extras,
        **warm_boot_extras,
        **chaos_extras,
        **tenancy_extras,
        **scenario_extras,
        **soak_extras,
        **fleet_extras,
        **control_extras,
        "chained_iters": ITERS,
        "tunnel_rtt_ms": round(rtt * 1000, 1),
        "packing_host_ms": round(packing_host_ms, 1),
        # raw env setting (0 = auto) AND the resolved worker count the
        # native scan actually runs with on this host (BENCH_r05's bare
        # `0` was ambiguous)
        "native_parse_threads": native_mod.parse_threads(),
        "native_parse_threads_effective": native_mod.effective_parse_threads(),
        "timing_method": (
            "headline: deployed streaming route (DataProcessor."
            "ingest_raw_stream over paginated chunks at the deployed "
            "default width) at BASELINE workload shape (1k svc / 10k "
            "endpoints / >=100k edges), STEADY-STATE: one persistent "
            "processor serves every rep a fresh window with distinct "
            "trace ids and identical naming shapes — production after "
            "boot; cold first window in e2e_stream_cold_*, r4-style "
            "legacy shape (fresh processor per rep) in "
            "e2e_stream_legacy_*; a virtual clock advances past the "
            "5-min dedup TTL between reps so the processed-trace map "
            "holds its production steady size. Best-of-6 critical path "
            "from measured "
            "per-chunk phases with ONLY the measured host->device copy "
            "excluded (dev-harness tunnel ~10 MB/s; PCIe on a TPU VM); "
            "measured tunnel-inclusive walls reported in "
            "e2e_stream_wall_reps_ms. Throughput estimators are BEST-of-N "
            "(noise on this 1-core host is strictly additive; rep lists "
            "in extras); latency metrics (graph refresh p50, HTTP, DP "
            "tick) are median-of-N. Serial one-shot path in e2e_serial_*; "
            "device-chain extra: fori_loop-chained kernels, rtt-adjusted; "
            "columnar (KMZC) decode of the identical window in "
            "e2e_wire_*/e2e_columnar_* (encode uncounted — the filter "
            "pays it), double-buffered upload pipeline counters in "
            "e2e_upload_* (blocked_ms = host wall actually spent waiting "
            "on transfers). "
            "XLA persistent compilation cache ON by default (repo-local "
            ".xla-cache), matching the deployed configuration "
            "(deploy/kmamiz-tpu.yaml wires KMAMIZ_COMPILE_CACHE_DIR); "
            "KMAMIZ_BENCH_NO_COMPILE_CACHE=1 forces a fully cold run"
        ),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(result))
    sys.stdout.flush()
    # the axon PJRT plugin can panic in its destructor during interpreter
    # teardown (client refs already destroyed); the result is printed, so
    # exit cleanly without running destructors
    os._exit(0)


if __name__ == "__main__":
    main()
