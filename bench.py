"""Benchmark: span-window ingest throughput + graph-metric refresh latency.

Run on real TPU hardware by the driver. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Workload (BASELINE.json configs): a MicroViSim-scale synthetic mesh with
1k services / 10k endpoints and a 1M-span window — the reference caps at
2,500 traces per 5 s tick (~<20k spans/sec sustained; see BASELINE.md), and
the north-star target is >=1M spans/sec with p50 full risk+instability graph
refresh < 50 ms at 10k endpoints.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

N_SPANS = 1 << 20  # ~1M spans per window
N_ENDPOINTS = 10_000
N_SERVICES = 1_000
N_STATUSES = 8
MAX_DEPTH = 8
GRAPH_EDGES = 50_000
BASELINE_SPANS_PER_SEC = 1_000_000.0  # BASELINE.json north star


def main() -> None:
    import jax
    import jax.numpy as jnp

    from kmamiz_tpu.ops import scorers, window

    rng = np.random.default_rng(0)

    # ---- window pipeline: 1M-span synthetic window -------------------------
    endpoint_id = jnp.asarray(rng.integers(0, N_ENDPOINTS, N_SPANS, dtype=np.int32))
    status_id = jnp.asarray(rng.integers(0, N_STATUSES, N_SPANS, dtype=np.int32))
    status_class = jnp.asarray(
        rng.choice([2, 4, 5], N_SPANS, p=[0.95, 0.04, 0.01]).astype(np.int8)
    )
    latency = jnp.asarray(rng.gamma(2.0, 50.0, N_SPANS).astype(np.float32))
    ts_rel = jnp.asarray(rng.integers(0, 30_000_000, N_SPANS, dtype=np.int32))
    valid = jnp.ones(N_SPANS, dtype=bool)

    # forest of ~7-span traces, alternating CLIENT/SERVER
    parent = np.arange(-1, N_SPANS - 1, dtype=np.int32)
    parent[::7] = -1
    kind = np.full(N_SPANS, 1, dtype=np.int8)
    kind[1::2] = 2
    parent = jnp.asarray(parent)
    kind_a = jnp.asarray(kind)

    def window_pipeline():
        stats = window.window_stats(
            endpoint_id,
            status_id,
            status_class,
            latency,
            ts_rel,
            valid,
            num_endpoints=N_ENDPOINTS,
            num_statuses=N_STATUSES,
        )
        edges = window.dependency_edges(
            parent, kind_a, valid, endpoint_id, max_depth=MAX_DEPTH
        )
        # every field returned and gated: each stage is its own jitted
        # executable (all outputs always computed), so this is belt-and-
        # braces against a future refactor jitting the whole pipeline,
        # where caller-side DCE would become possible
        return tuple(stats) + tuple(edges)

    # warmup/compile
    out = window_pipeline()
    jax.block_until_ready(out)

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = window_pipeline()
    jax.block_until_ready(out)
    ingest_dt = (time.perf_counter() - t0) / iters
    spans_per_sec = N_SPANS / ingest_dt

    # ---- graph metric refresh @10k endpoints -------------------------------
    ep_service = jnp.asarray(
        rng.integers(0, N_SERVICES, N_ENDPOINTS, dtype=np.int32)
    )
    ep_ml = jnp.asarray(rng.integers(0, 4096, N_ENDPOINTS, dtype=np.int32))
    ep_record = jnp.ones(N_ENDPOINTS, dtype=bool)
    src = jnp.asarray(rng.integers(0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32))
    dst = jnp.asarray(rng.integers(0, N_ENDPOINTS, GRAPH_EDGES, dtype=np.int32))
    dist = jnp.asarray(rng.integers(1, MAX_DEPTH, GRAPH_EDGES, dtype=np.int32))
    emask = jnp.ones(GRAPH_EDGES, dtype=bool)
    req_count = jnp.asarray(rng.gamma(2.0, 100.0, N_SERVICES).astype(np.float32))
    err_count = req_count * 0.01
    cv_w = req_count * 0.5
    replicas = jnp.ones(N_SERVICES, dtype=jnp.float32)
    active = jnp.ones(N_SERVICES, dtype=bool)

    def graph_refresh():
        s = scorers.service_scores(
            src, dst, dist, emask, ep_service, ep_ml, ep_record,
            num_services=N_SERVICES,
        )
        coh = scorers.usage_cohesion(
            src, dst, dist, emask, ep_service, ep_record,
            num_services=N_SERVICES,
        )
        risk = scorers.risk_scores(
            s.relying_factor, s.acs, replicas, req_count, err_count, cv_w, active
        )
        # all fields gated (see note in window_pipeline)
        return tuple(s) + tuple(coh) + tuple(risk)

    out = graph_refresh()
    jax.block_until_ready(out)

    times = []
    for _ in range(30):
        t0 = time.perf_counter()
        out = graph_refresh()
        jax.block_until_ready(out)  # gate on every output, not just risk
        times.append(time.perf_counter() - t0)
    p50_refresh_ms = float(np.percentile(times, 50) * 1000)

    result = {
        "metric": "span ingest throughput (window stats + dependency edges, 1M-span window)",
        "value": round(spans_per_sec, 0),
        "unit": "spans/sec",
        "vs_baseline": round(spans_per_sec / BASELINE_SPANS_PER_SEC, 3),
        "p50_graph_refresh_ms_10k_endpoints": round(p50_refresh_ms, 2),
        "graph_refresh_target_ms": 50.0,
        "n_spans": N_SPANS,
        "n_endpoints": N_ENDPOINTS,
        "n_services": N_SERVICES,
        "device": str(__import__("jax").devices()[0]),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
