# KMamiz-TPU: API server + TPU Data Processor in one image.
#
#   docker build -t kmamiz-tpu .
#   docker run -p 3000:3000 -e STORAGE_URI=file:///data kmamiz-tpu
#
# The CPU jax wheel is installed by default so the image runs anywhere;
# on a TPU VM, build with --build-arg JAX_EXTRA="jax[tpu]" (libtpu wheel)
# and the same image drives real chips.
FROM python:3.11-slim AS build

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

ARG JAX_EXTRA="jax[cpu]"
RUN pip install --no-cache-dir "${JAX_EXTRA}" flax optax orbax-checkpoint chex einops numpy

WORKDIR /app
COPY kmamiz_tpu/ kmamiz_tpu/
COPY native/kmamiz_native.cpp native/kmamiz_json.cpp native/kmamiz_spans.cpp native/
# filter CRs + wasm filter source; the header-telemetry binary is
# (re)assembled below, the richer Go build (JSON body capture) comes from
# envoy/filter/build.sh on a tinygo-equipped machine
COPY envoy/ envoy/
COPY dist/ dist/
COPY tools/wasm_asm.py tools/build_wasm_filter.py tools/

# compile the native ingest/parse extension at build time so the first
# request never pays the toolchain cost
RUN g++ -O3 -shared -fPIC -pthread -std=c++17 \
      -o /tmp/libkmamiz_native.so \
      native/kmamiz_native.cpp native/kmamiz_json.cpp native/kmamiz_spans.cpp \
    && mkdir -p native/build \
    && mv /tmp/libkmamiz_native.so native/build/

# assemble the proxy-wasm telemetry filter from the tree (pure Python —
# no wasm toolchain needed); served at GET /wasm
RUN python tools/build_wasm_filter.py

ENV PYTHONPATH=/app \
    PORT=3000 \
    STORAGE_URI=memory:// \
    KMAMIZ_WASM_PATH=/app/envoy/filter/kmamiz_filter.wasm

EXPOSE 3000
# modes mirror the reference entrypoint (index.ts:29-92): SERVE_ONLY,
# READ_ONLY_MODE, SIMULATOR_MODE, ENABLE_TESTING_ENDPOINTS via env
CMD ["python", "-m", "kmamiz_tpu.api.app"]
